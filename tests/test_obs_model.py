"""Model.analyzeCases observability smoke test (acceptance criterion).

One coarse-grid run of the full statics/dynamics/QTF/outputs pipeline
must produce (a) a Chrome trace with correctly nested phase spans, (b) a
metrics snapshot with per-case fixed-point iteration/residual series and
a dynamics condition-number gauge, and (c) a schema-valid run manifest —
written to the configured obs directory.

Uses the vendored Vertical_cylinder design (no turbine — keeps the
compile budget small) with internal second-order forces switched on so
the calcQTF_slenderBody span is exercised too.  The OC3 spar runs the
same instrumentation end-to-end in tests/test_model_oc3.py (slow tier).
"""
import json
import os

import pytest

from raft_tpu import obs
from raft_tpu.io.designs import load_design
from raft_tpu.model import Model


@pytest.fixture(scope="module")
def analyzed(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("obs_out"))
    obs.reset_tracing()
    obs.REGISTRY.reset()
    obs.configure(out_dir)
    design = load_design("Vertical_cylinder")
    design.setdefault("settings", {})
    design["settings"]["min_freq"] = 0.05
    design["settings"]["max_freq"] = 0.5
    design["platform"]["potSecOrder"] = 1      # exercise the QTF phase
    design["platform"]["min_freq2nd"] = 0.05
    design["platform"]["max_freq2nd"] = 0.25
    model = Model(design)
    model.analyzeCases()
    yield model, out_dir
    obs.configure(None)
    obs.reset_tracing()
    obs.REGISTRY.reset()


def test_phase_spans_recorded(analyzed):
    model, _ = analyzed
    agg = obs.aggregate()
    for phase in ("analyzeCases", "solveStatics", "solveDynamics",
                  "fowt_linearize", "calcQTF_slenderBody",
                  "saveTurbineOutputs"):
        assert phase in agg, f"missing span {phase!r}"
        assert agg[phase][1] >= 1
    # nesting: the linearization span is a child of solveDynamics
    spans = {e["name"]: e for e in obs.spans()}
    assert spans["fowt_linearize"]["parent"] == "solveDynamics"
    assert spans["solveDynamics"]["parent"] == "analyzeCases"
    assert spans["solveStatics"]["parent"] == "analyzeCases"


def test_fixed_point_and_condition_metrics(analyzed):
    snap = obs.snapshot()
    hist = snap["raft_fixed_point_iterations"]
    assert hist["kind"] == "histogram"
    series = hist["series"]
    assert series and all(s["count"] >= 1 for s in series)
    # per-load-case labelling
    assert any(s["labels"].get("case") == "0" for s in series)
    res = snap["raft_fixed_point_residual"]
    assert all(s["value"] >= 0.0 for s in res["series"])
    cond = snap["raft_dynamics_condition_number"]
    assert all(s["value"] >= 1.0 for s in cond["series"])
    dyn_res = snap["raft_dynamics_solve_residual"]
    assert all(s["value"] < 1e-4 for s in dyn_res["series"])
    stat = snap["raft_statics_newton_iterations"]
    assert stat["series"][0]["count"] >= 1
    # the Prometheus view renders without error and carries the series
    text = obs.to_prometheus()
    assert "raft_fixed_point_iterations_bucket" in text
    assert "raft_dynamics_condition_number" in text


def test_manifest_and_trace_written(analyzed):
    model, out_dir = analyzed
    manifest = model.last_manifest
    assert manifest is not None and manifest.status == "ok"
    doc = manifest.to_dict()
    assert obs.validate_manifest(doc) == []
    assert doc["kind"] == "analyzeCases"
    assert doc["config"]["nCases"] == 1
    assert doc["environment"]["backend"] == "cpu"
    phase_names = {p["name"] for p in doc["phases"]}
    assert {"solveStatics", "solveDynamics",
            "calcQTF_slenderBody"} <= phase_names
    assert "raft_fixed_point_iterations" in doc["metrics"]

    files = sorted(os.listdir(out_dir))
    mani_files = [f for f in files if f.endswith(".manifest.json")]
    trace_files = [f for f in files if f.endswith(".trace.json")]
    assert len(mani_files) == 1 and len(trace_files) == 1
    on_disk = json.load(open(os.path.join(out_dir, mani_files[0])))
    assert obs.validate_manifest(on_disk) == []
    trace = json.load(open(os.path.join(out_dir, trace_files[0])))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"analyzeCases", "solveStatics", "solveDynamics"} <= names
