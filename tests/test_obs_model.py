"""Model.analyzeCases observability smoke test (acceptance criterion).

One coarse-grid run of the full statics/dynamics/QTF/outputs pipeline
must produce (a) a Chrome trace with correctly nested phase spans, (b) a
metrics snapshot with per-case fixed-point iteration/residual series and
a dynamics condition-number gauge, (c) a schema-valid run manifest —
written to the configured obs directory — and (d) a schema-valid result
ledger with per-case RAO/response digests (the regression sentinel's
input).

Uses the vendored Vertical_cylinder design (no turbine — keeps the
compile budget small) with internal second-order forces switched on so
the calcQTF_slenderBody span is exercised too.  The OC3 spar runs the
same instrumentation end-to-end in tests/test_regression_sentinel.py
(slow tier).

The conftest autouse fixture resets ALL obs state around every test, so
the module-scoped run below captures everything it asserts on (spans,
aggregate, metrics snapshot, ledger) at fixture time.
"""
import json
import os

import pytest

from raft_tpu import obs
from raft_tpu.io.designs import load_design
from raft_tpu.model import Model


@pytest.fixture(scope="module")
def analyzed(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("obs_out"))
    obs.reset_all()
    obs.configure(out_dir)
    design = load_design("Vertical_cylinder")
    design.setdefault("settings", {})
    design["settings"]["min_freq"] = 0.05
    design["settings"]["max_freq"] = 0.5
    design["platform"]["potSecOrder"] = 1      # exercise the QTF phase
    design["platform"]["min_freq2nd"] = 0.05
    design["platform"]["max_freq2nd"] = 0.25
    model = Model(design)
    model.analyzeCases()
    state = {
        "model": model,
        "out_dir": out_dir,
        "agg": obs.aggregate(),
        "spans": obs.spans(),
        "snap": obs.snapshot(),
        "prom": obs.to_prometheus(),
    }
    yield state
    obs.reset_all()


def test_phase_spans_recorded(analyzed):
    agg = analyzed["agg"]
    for phase in ("analyzeCases", "solveStatics", "solveDynamics",
                  "fowt_linearize", "calcQTF_slenderBody",
                  "saveTurbineOutputs"):
        assert phase in agg, f"missing span {phase!r}"
        assert agg[phase][1] >= 1
    # nesting: the linearization span is a child of solveDynamics
    spans = {e["name"]: e for e in analyzed["spans"]}
    assert spans["fowt_linearize"]["parent"] == "solveDynamics"
    assert spans["solveDynamics"]["parent"] == "analyzeCases"
    assert spans["solveStatics"]["parent"] == "analyzeCases"


def test_fixed_point_and_condition_metrics(analyzed):
    snap = analyzed["snap"]
    hist = snap["raft_fixed_point_iterations"]
    assert hist["kind"] == "histogram"
    series = hist["series"]
    assert series and all(s["count"] >= 1 for s in series)
    # per-load-case labelling
    assert any(s["labels"].get("case") == "0" for s in series)
    res = snap["raft_fixed_point_residual"]
    assert all(s["value"] >= 0.0 for s in res["series"])
    cond = snap["raft_dynamics_condition_number"]
    assert all(s["value"] >= 1.0 for s in cond["series"])
    dyn_res = snap["raft_dynamics_solve_residual"]
    assert all(s["value"] < 1e-4 for s in dyn_res["series"])
    stat = snap["raft_statics_newton_iterations"]
    assert stat["series"][0]["count"] >= 1
    # the Prometheus view renders without error and carries the series
    text = analyzed["prom"]
    assert "raft_fixed_point_iterations_bucket" in text
    assert "raft_dynamics_condition_number" in text


def test_manifest_and_trace_written(analyzed):
    model, out_dir = analyzed["model"], analyzed["out_dir"]
    manifest = model.last_manifest
    assert manifest is not None and manifest.status == "ok"
    doc = manifest.to_dict()
    assert obs.validate_manifest(doc) == []
    assert doc["kind"] == "analyzeCases"
    assert doc["config"]["nCases"] == 1
    assert doc["environment"]["backend"] == "cpu"
    phase_names = {p["name"] for p in doc["phases"]}
    assert {"solveStatics", "solveDynamics",
            "calcQTF_slenderBody"} <= phase_names
    assert "raft_fixed_point_iterations" in doc["metrics"]

    files = sorted(os.listdir(out_dir))
    mani_files = [f for f in files if f.endswith(".manifest.json")]
    trace_files = [f for f in files if f.endswith(".trace.json")]
    assert len(mani_files) == 1 and len(trace_files) == 1
    on_disk = json.load(open(os.path.join(out_dir, mani_files[0])))
    assert obs.validate_manifest(on_disk) == []
    trace = json.load(open(os.path.join(out_dir, trace_files[0])))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"analyzeCases", "solveStatics", "solveDynamics"} <= names


def test_build_info_and_device_telemetry_in_manifest(analyzed):
    snap = analyzed["snap"]
    (s,) = snap["raft_tpu_build_info"]["series"]
    assert s["value"] == 1.0 and "git_sha" in s["labels"]
    doc = analyzed["model"].last_manifest.to_dict()
    telem = doc["extra"]["device_telemetry"]
    assert "devices" in telem and "live_arrays" in telem
    la = telem["live_arrays"]
    assert la is None or (la["count"] >= 0 and la["total_bytes"] >= 0)
    # the batched dynamics solve got a static HLO cost analysis
    assert "raft_hlo_flops" in snap
    assert any(s["labels"].get("kernel") == "dynamics_system_solve"
               for s in snap["raft_hlo_flops"]["series"])


def test_ledger_written_and_valid(analyzed):
    from raft_tpu.obs import ledger as L

    model, out_dir = analyzed["model"], analyzed["out_dir"]
    led = model.last_ledger
    assert led is not None
    assert L.validate_ledger(led) == []
    keys = [e["key"] for e in led["entries"]]
    assert "case0/fowt0" in keys and "case0/system" in keys
    fowt0 = next(e for e in led["entries"] if e["key"] == "case0/fowt0")
    # the RAO fingerprint and the solver facts both made it in
    assert "rao_mag_max_surge" in fowt0["metrics"]
    assert "std_heave" in fowt0["metrics"]
    assert "drag_iters" in fowt0["metrics"]
    system = next(e for e in led["entries"] if e["key"] == "case0/system")
    assert "cond_max" in system["metrics"]
    assert "statics_iters" in system["metrics"]
    # on-disk copy next to the manifest, identical digest
    ledger_files = [f for f in os.listdir(out_dir)
                    if f.endswith(".ledger.json")]
    assert len(ledger_files) == 1
    on_disk = L.load_ledger(os.path.join(out_dir, ledger_files[0]))
    assert L.validate_ledger(on_disk) == []
    assert on_disk["digest"] == led["digest"]
    # a self-diff of the persisted ledger reports zero regressions
    report = L.diff(led, on_disk)
    assert report["ok"] and report["identical"]
