"""RAFT_OMDAO adapter: design-dict round trip + end-to-end replay.

Mirrors the spirit of the reference's omdao regression tests
(reference: tests/test_omdao_OC3spar.py:9-60) without WEIS: the OC3spar
design yaml is mapped to OpenMDAO-style options/inputs
(`omdao_from_design`), driven through `RAFT_OMDAO.compute`, and the
rebuilt design + outputs are checked against the direct Model path.
"""
import os

import numpy as np
import pytest
import yaml

from raft_tpu.omdao import (RAFT_OMDAO, RAFT_OMDAO_Standalone, RAFT_Group,
                            omdao_from_design)

REF_DESIGNS = "/root/reference/designs"


def _oc3_design():
    with open(os.path.join(REF_DESIGNS, "OC3spar.yaml")) as f:
        design = yaml.safe_load(f)
    # one spectral-wind DLC that the adapter keeps + one non-spectral row
    # that its case filter must drop (reference: omdao_raft.py:676-686)
    design["cases"]["data"] = [
        [10, 0, "IB_NTM", "operating", 0, "JONSWAP", 8, 2, 0],
        [12, 0, 0.1, "operating", 0, "JONSWAP", 9, 4, 0],
    ]
    return design


@pytest.fixture(scope="module")
def oc3_om():
    design = _oc3_design()
    options, inputs, discrete_inputs = omdao_from_design(design)
    comp = RAFT_OMDAO_Standalone(**options)
    outputs = comp.run(inputs, discrete_inputs)
    return design, comp, inputs, discrete_inputs, outputs


def test_design_round_trip(oc3_om):
    """design -> OM inputs -> build_design reproduces the yaml geometry."""
    design, comp, inputs, discrete_inputs, _ = oc3_om
    rebuilt, case_mask = comp.build_design(comp._inputs, comp._discrete_inputs)

    assert case_mask == [True, False]
    assert len(rebuilt["cases"]["data"]) == 1

    mem0 = design["platform"]["members"][0]
    rmem0 = rebuilt["platform"]["members"][0]
    np.testing.assert_allclose(rmem0["rA"], mem0["rA"])
    np.testing.assert_allclose(rmem0["rB"], mem0["rB"])
    st0 = np.unique(np.asarray(mem0["stations"], float))
    np.testing.assert_allclose(rmem0["stations"],
                               (st0 - st0[0]) / (st0[-1] - st0[0]))
    np.testing.assert_allclose(rmem0["d"], mem0["d"])
    np.testing.assert_allclose(rmem0["t"], mem0["t"])
    assert rmem0["rho_shell"] == mem0["rho_shell"]

    tow = design["turbine"]["tower"]
    rtow = rebuilt["turbine"]["tower"]
    stt = np.asarray(tow["stations"], float)
    np.testing.assert_allclose(rtow["stations"],
                               (stt - stt[0]) / (stt[-1] - stt[0]))
    np.testing.assert_allclose(rtow["d"], tow["d"])

    assert rebuilt["site"]["water_depth"] == design["site"]["water_depth"]
    for i, ln in enumerate(design["mooring"]["lines"]):
        assert rebuilt["mooring"]["lines"][i]["length"] == ln["length"]
    lt = design["mooring"]["line_types"][0]
    rlt = rebuilt["mooring"]["line_types"][0]
    for key in ("diameter", "mass_density", "stiffness"):
        assert rlt[key] == float(lt[key])   # yaml may hold '384.243e6' str

    blade = np.asarray(design["turbine"]["blade"]["geometry"], float)
    np.testing.assert_allclose(rebuilt["turbine"]["blade"]["geometry"],
                               blade)


def test_outputs_match_direct_model(oc3_om):
    """OM outputs equal a direct Model run on the rebuilt design."""
    from raft_tpu.model import Model

    design, comp, inputs, discrete_inputs, outputs = oc3_om
    rebuilt, _mask = comp.build_design(comp._inputs, comp._discrete_inputs)

    model = Model(rebuilt)
    model.analyzeUnloaded()
    # compute() solves eigen after the (last) loaded case; reproduce that
    # statics state without re-paying for the dynamics
    case = dict(zip(rebuilt["cases"]["keys"], rebuilt["cases"]["data"][0]))
    model.solveStatics(case)
    results = model.calcOutputs()
    fns, _ = model.solveEigen()

    props = results["properties"]
    assert outputs["properties_total mass"] == pytest.approx(
        props["total mass"], rel=1e-8)
    assert outputs["properties_substructure mass"] == pytest.approx(
        props["substructure mass"], rel=1e-8)
    np.testing.assert_allclose(outputs["properties_center of buoyancy"],
                               props["center of buoyancy"], atol=1e-8)
    np.testing.assert_allclose(outputs["properties_C_lines0"],
                               props["C_lines0"], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(outputs["rigid_body_periods"]), 1.0 / fns[:6], rtol=1e-6)

    # property sanity vs OC3 physical values
    assert 7.0e6 < outputs["properties_substructure mass"] < 8.5e6
    assert outputs["properties_buoyancy (pgV)"] > 7.5e7


def test_case_stats_and_aggregates(oc3_om):
    """Filtered case rows stay zero; aggregates track the stats arrays."""
    _design, _comp, _inputs, _dis, outputs = oc3_om

    # row 0 = spectral case (filled), row 1 = filtered (zeros)
    assert outputs["stats_surge_std"][0] > 0.0
    assert outputs["stats_surge_std"][1] == 0.0
    assert outputs["stats_pitch_max"][0] > 0.0
    assert np.any(outputs["stats_Tmoor_avg"][0] > 0.0)
    assert np.all(outputs["stats_Tmoor_avg"][1] == 0.0)
    psd = outputs["stats_surge_PSD"]
    assert psd.shape[0] == 2 and np.any(psd[0] > 0) and np.all(psd[1] == 0)

    assert outputs["Max_PtfmPitch"] == pytest.approx(
        outputs["stats_pitch_max"][0])
    assert outputs["Std_PtfmPitch"] == pytest.approx(
        outputs["stats_pitch_std"][0])
    assert outputs["Max_Offset"] == pytest.approx(np.sqrt(
        outputs["stats_surge_max"][0] ** 2 + outputs["stats_sway_max"][0] ** 2))
    assert outputs["platform_mass"] == pytest.approx(
        outputs["properties_substructure mass"])
    assert outputs["platform_displacement"] > 7000.0

    # natural periods present and physical for OC3 (surge ~100s+, heave ~30s)
    assert outputs["surge_period"] > 60.0
    assert 20.0 < outputs["heave_period"] < 40.0


def test_group_wrapper():
    """RAFT_Group promotes a RAFT_OMDAO subsystem (reference:
    omdao_raft.py:816-831)."""
    design = _oc3_design()
    options, _inputs, _dis = omdao_from_design(design)
    grp = RAFT_Group(**options)
    grp.setup()
    sub = getattr(grp, "_subsystems", {}).get("raft")
    if sub is not None:        # shim path
        assert isinstance(sub, RAFT_OMDAO)
