"""WEIS-inputs replay through the OpenMDAO adapter.

Feeds the exact options+inputs dump that WEIS generated for the
reference's 15_RAFT_Studies example (reference:
tests/test_omdao_VolturnUS-S.py:20-45 replaying
tests/test_data/weis_options.yaml / weis_inputs.yaml, produced by the
DEBUG_OMDAO hook at omdao_raft.py:9,362-386) through our adapter's full
input surface.  The reference test is smoke-only (run_model with no
asserts); here the DLC list is truncated for runtime and the structural
outputs are additionally sanity-checked, which the reference never does.
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow
import yaml

from raft_tpu.omdao import RAFT_OMDAO_Standalone

DATA = "/root/reference/tests/test_data"


@pytest.fixture(scope="module")
def weis_replay():
    opt_path = os.path.join(DATA, "weis_options.yaml")
    in_path = os.path.join(DATA, "weis_inputs.yaml")
    if not (os.path.isfile(opt_path) and os.path.isfile(in_path)):
        pytest.skip("WEIS dump files not available")
    opt = yaml.safe_load(open(opt_path))
    inputs = yaml.safe_load(open(in_path))
    mo = opt["modeling_options"]
    # truncate the 98-DLC list for test runtime; the input-surface mapping
    # (the point of the replay) is unaffected by the case count
    mo["raft_dlcs"] = mo["raft_dlcs"][:1]
    mo["n_cases"] = 1
    mo["runPyHAMS"] = False
    kwargs = dict(
        modeling_options=mo,
        analysis_options=opt["analysis_options"],
        turbine_options=opt["turbine_options"],
        mooring_options=opt["mooring_options"],
        member_options=opt["member_options"])
    # declaration check BEFORE the run: prime() raises on the first
    # unknown key, so collect the full unmapped list from a bare setup
    comp = RAFT_OMDAO_Standalone(**kwargs)
    comp.prime()
    known = set(comp._inputs) | set(comp._discrete_inputs)
    unknown = [k for k in inputs if k not in known]
    # run() re-primes with the overlay on the already-setup vectors
    outputs = comp.run(inputs) if not unknown else None
    return comp, inputs, outputs, unknown


def test_all_weis_inputs_recognized(weis_replay):
    """Every key in the WEIS input dump must map onto a declared input
    (continuous or discrete) — missing declarations would silently drop
    optimizer-controlled design variables."""
    _, _, _, unknown = weis_replay
    assert unknown == [], unknown


def test_replay_outputs_sane(weis_replay):
    comp, _, out, unknown = weis_replay
    assert not unknown
    periods = np.asarray(out["rigid_body_periods"])
    assert periods.shape == (6,)
    # VolturnUS-S-family: long surge/sway, heave ~15-25 s, pitch 20-35 s
    assert 60 < periods[0] < 250 and 60 < periods[1] < 250
    assert 10 < periods[2] < 30
    assert 15 < periods[4] < 40
    assert float(out["properties_substructure mass"]) > 1e7
    # reference semantics: max over cases of (pitch_avg + 3 sigma), no abs
    # (omdao_raft.py:797) — slightly negative at the 3 m/s DLC
    assert -2.0 < float(out["Max_PtfmPitch"]) < 10.0
    assert 0 < float(out["Max_Offset"]) < 50.0
    assert float(out["max_nac_accel"]) > 0
    stats = np.atleast_1d(out["stats_pitch_std"])
    assert stats.shape[0] == 1 and np.all(np.isfinite(stats))
