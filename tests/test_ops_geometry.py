"""Frustum volume/centroid/inertia kernels vs the reference closed forms
(reference: tests/test_helpers.py:14-23 values; raft/raft_member.py:321-402
formulas re-derived here in plain numpy)."""
import numpy as np
from numpy.testing import assert_allclose

from raft_tpu.ops import geometry as geo


def test_frustum_vcv_circ():
    V, hc = geo.frustum_vcv_circ(2.0, 1.0, 2.0)
    assert_allclose([float(V), float(hc)], [3.665191429188092, 0.7857142857142856],
                    rtol=1e-5)
    # zero-size frustum
    V0, hc0 = geo.frustum_vcv_circ(0.0, 0.0, 1.0)
    assert float(V0) == 0.0 and float(hc0) == 0.0


def test_frustum_vcv_rect():
    V, hc = geo.frustum_vcv_rect(np.array([2.0, 1.0]), np.array([1.0, 0.5]), 2.0)
    assert_allclose([float(V), float(hc)], [2.3333333333333335, 0.7857142857142857],
                    rtol=1e-5)


def test_frustum_moi_circ_cylinder():
    d, H, p = 5.0, 12.0, 850.0
    r = d / 2
    Ixx, Izz = geo.frustum_moi_circ(d, d, H, p)
    I_rad = (1 / 12) * (p * H * np.pi * r**2) * (3 * r**2 + 4 * H**2)
    I_ax = 0.5 * p * np.pi * H * r**4
    assert_allclose(float(Ixx), I_rad, rtol=1e-10)
    assert_allclose(float(Izz), I_ax, rtol=1e-10)


def test_frustum_moi_circ_tapered():
    dA, dB, H, p = 4.0, 6.0, 10.0, 850.0
    r1, r2 = dA / 2, dB / 2
    Ixx, Izz = geo.frustum_moi_circ(dA, dB, H, p)
    I_rad = (1 / 20) * p * np.pi * H * (r2**5 - r1**5) / (r2 - r1) \
        + (1 / 30) * p * np.pi * H**3 * (r1**2 + 3 * r1 * r2 + 6 * r2**2)
    I_ax = (1 / 10) * p * np.pi * H * (r2**5 - r1**5) / (r2 - r1)
    assert_allclose(float(Ixx), I_rad, rtol=1e-10)
    assert_allclose(float(Izz), I_ax, rtol=1e-10)


def test_frustum_moi_rect_cuboid():
    L, W, H, p = 3.0, 2.0, 7.0, 1000.0
    M = p * L * W * H
    Ixx, Iyy, Izz = geo.frustum_moi_rect(np.array([L, W]), np.array([L, W]), H, p)
    assert_allclose(float(Ixx), (1 / 12) * M * (W**2 + 4 * H**2), rtol=1e-10)
    assert_allclose(float(Iyy), (1 / 12) * M * (L**2 + 4 * H**2), rtol=1e-10)
    assert_allclose(float(Izz), (1 / 12) * M * (L**2 + W**2), rtol=1e-10)


def test_frustum_moi_rect_tapered():
    La, Wa, Lb, Wb, H, p = 4.0, 3.0, 2.0, 1.5, 6.0, 500.0
    Ixx, Iyy, Izz = geo.frustum_moi_rect(np.array([La, Wa]), np.array([Lb, Wb]), H, p)
    # truncated-pyramid closed forms (both side pairs taper)
    x2 = (1 / 12) * p * ((Lb - La)**3 * H * (Wb / 5 + Wa / 20)
                         + (Lb - La)**2 * La * H * (3 * Wb / 4 + Wa / 4)
                         + (Lb - La) * La**2 * H * (Wb + Wa / 2)
                         + La**3 * H * (Wb / 2 + Wa / 2))
    y2 = (1 / 12) * p * ((Wb - Wa)**3 * H * (Lb / 5 + La / 20)
                         + (Wb - Wa)**2 * Wa * H * (3 * Lb / 4 + La / 4)
                         + (Wb - Wa) * Wa**2 * H * (Lb + La / 2)
                         + Wa**3 * H * (Lb / 2 + La / 2))
    z2 = p * (Wb * Lb / 5 + Wa * Lb / 20 + La * Wb / 20 + Wa * La / 30) * H**3
    assert_allclose(float(Ixx), y2 + z2, rtol=1e-10)
    assert_allclose(float(Iyy), x2 + z2, rtol=1e-10)
    assert_allclose(float(Izz), x2 + y2, rtol=1e-10)


def test_frustum_moi_rect_prism():
    # only widths taper (truncated triangular prism)
    La, Wa, Lb, Wb, H, p = 3.0, 2.0, 3.0, 1.0, 5.0, 800.0
    Ixx, Iyy, Izz = geo.frustum_moi_rect(np.array([La, Wa]), np.array([Lb, Wb]), H, p)
    L = La
    x2 = (1 / 24) * p * L**3 * H * (Wb + Wa)
    y2 = (1 / 48) * p * L * H * (Wb**3 + Wa * Wb**2 + Wa**2 * Wb + Wa**3)
    z2 = (1 / 12) * p * L * H**3 * (3 * Wb + Wa)
    assert_allclose(float(Ixx), y2 + z2, rtol=1e-10)
    assert_allclose(float(Iyy), x2 + z2, rtol=1e-10)
    assert_allclose(float(Izz), x2 + y2, rtol=1e-10)


def test_frustum_moi_ulp_taper_is_cylinder():
    """Derived cap diameters like dB*(dAi/dA) can carry a 1-ulp taper; the
    tapered closed form divides (rB^5-rA^5)/(rB-rA) and would return
    catastrophic-cancellation noise (the reference's exact dA==dB check
    has this failure, raft_member.py:327-336).  The relative-tolerance
    cylinder branch must give the exact cylinder values instead."""
    from raft_tpu.ops.geometry import frustum_moi_circ

    d = 12.0
    d_ulp = 23.88 * (12.0 / 23.88)      # 12 +/- 1 ulp
    h, rho = 0.06, 7850.0
    Ix0, Iz0 = (np.asarray(a) for a in frustum_moi_circ(
        np.array([d]), np.array([d]), np.array([h]), rho))
    Ix1, Iz1 = (np.asarray(a) for a in frustum_moi_circ(
        np.array([d]), np.array([d_ulp]), np.array([h]), rho))
    assert np.allclose(Ix1, Ix0, rtol=1e-12)
    assert np.allclose(Iz1, Iz0, rtol=1e-12)
    # exact cylinder references: m(r^2/4 + h^2/3) about the end, m r^2/2
    r = d / 2
    m = rho * np.pi * r**2 * h
    assert np.allclose(float(Iz0[0]), 0.5 * m * r**2, rtol=1e-12)
    assert np.allclose(float(Ix0[0]), m * (r**2 / 4 + h**2 / 3), rtol=1e-12)
