"""Struve-minus-Bessel difference kernels vs scipy (in scipy's accurate
range) and vs asymptotic limits."""
import numpy as np
import pytest
from numpy.testing import assert_allclose

from raft_tpu.ops.special import (
    struve_bessel_diff_0,
    struve_bessel_diff_1,
    struve_bessel_diff_m2,
)

scipy_special = pytest.importorskip("scipy.special")


def test_vs_scipy_small_x():
    # scipy's naive difference is accurate for small/moderate x only
    x = np.linspace(1e-3, 12.0, 80)
    assert_allclose(np.asarray(struve_bessel_diff_0(x)),
                    scipy_special.modstruve(0, x) - scipy_special.iv(0, x),
                    rtol=1e-7)
    assert_allclose(np.asarray(struve_bessel_diff_1(x)),
                    scipy_special.modstruve(1, x) - scipy_special.iv(1, x),
                    rtol=1e-7)
    assert_allclose(np.asarray(struve_bessel_diff_m2(x)),
                    scipy_special.modstruve(-2, x) - scipy_special.iv(2, x),
                    rtol=1e-5)


def test_large_x_limits():
    # D_1 -> -2/pi; D_0 -> 0-; both finite where scipy's difference has
    # catastrophically cancelled (the reference zeroes resulting NaNs,
    # raft_rotor.py:1221 — we stay accurate instead)
    x = np.array([50.0, 100.0, 500.0, 5000.0])
    d1 = np.asarray(struve_bessel_diff_1(x))
    assert_allclose(d1, -2 / np.pi, rtol=1e-3)
    d0 = np.asarray(struve_bessel_diff_0(x))
    assert np.all(d0 < 0) and np.all(np.abs(d0) < 0.02)
    dm2 = np.asarray(struve_bessel_diff_m2(x))
    assert np.all(np.isfinite(dm2))


def test_zero_edge():
    assert float(struve_bessel_diff_1(0.0)) == 0.0
    assert float(struve_bessel_diff_0(0.0)) == -1.0
