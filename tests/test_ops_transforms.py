"""L0 transform kernels vs the reference's analytic unit-test values
(reference: tests/test_helpers.py)."""
import numpy as np
import pytest
from numpy.testing import assert_allclose

from raft_tpu.ops import transforms as tf


def test_small_rotate():
    r = np.array([1.0, 2.0, 3.0])
    th = np.array([5 + 3j, 3 + 5j, 4 + 3j]) * (np.pi / 180.0)
    rt = tf.small_rotate(r, th)
    desired = np.array([0.01745329 + 0.15707963j, -0.19198622 - 0.10471976j,
                        0.12217305 + 0.01745329j])
    assert_allclose(np.asarray(rt), desired, rtol=1e-5)


def test_vec_vec_trans():
    v = np.array([0.7 + 1.2j, 1.5 + 0.4j, 3.0 + 2.3j])
    desired = np.array([[-0.95 + 1.68j, 0.57 + 2.08j, -0.66 + 5.21j],
                        [0.57 + 2.08j, 2.09 + 1.2j, 3.58 + 4.65j],
                        [-0.66 + 5.21j, 3.58 + 4.65j, 3.71 + 13.8j]])
    assert_allclose(np.asarray(tf.vec_vec_trans(v)), desired, rtol=1e-5)


def test_translate_force_3to6():
    Fin = np.array([0.5 + 3j, 2.0 + 1.5j, 3.0 + 0.7j])
    r = np.array([1.0, 2.0, 3.0])
    desired = np.array([0.5 + 3.0j, 2.0 + 1.5j, 3.0 + 0.7j,
                        0.0 - 3.1j, -1.5 + 8.3j, 1.0 - 4.5j])
    assert_allclose(np.asarray(tf.translate_force_3to6(Fin, r)), desired, rtol=1e-5)


def test_transform_force():
    offset = np.array([10.0, 20.0, 30.0])
    f_in = np.array([0.5 + 3j, 2.0 + 1.5j, 3.0 + 0.7j])
    F_in = np.array([1.2 + 0.3j, 0.4 + 1.5j, 2.3 + 0.7j,
                     0.5 + 0.9j, 1.1 + 0.2j, 0.7 + 1.4j])
    R = tf.rotation_matrix(0.1, 0.2, 0.3)

    desired3 = np.array([0.57300698 + 2.54908178j, 1.94679387 + 2.27765615j,
                         3.02186311 + 0.23337633j, 2.03344603 - 63.66215798j,
                         -13.02842176 + 74.13869023j, 8.00779917 - 28.20507416j])
    assert_allclose(np.asarray(tf.transform_force(f_in, offset=offset, rotmat=R)),
                    desired3, rtol=1e-5)

    desired6 = np.array([1.51572022 + 2.10897023e-02j, 0.64512428 + 1.49565656j,
                         2.04362591 + 7.69783522e-01j, 21.83717669 - 2.83806906e+01j,
                         26.20635997 - 6.66493243j, -23.17224939 + 1.57407763e+01j])
    assert_allclose(np.asarray(tf.transform_force(F_in, offset=offset, rotmat=R)),
                    desired6, rtol=1e-5)


def test_translate_matrix_3to6():
    Min = np.array([[0.73, 2.41, 3.88], [1.25, 9.12, 5.79], [5.37, 7.94, 8.63]])
    r = np.array([10.0, 20.0, 30.0])
    desired = np.array(
        [[7.300e-01, 2.410e+00, 3.880e+00, 5.300e+00, -1.690e+01, 9.500e+00],
         [1.250e+00, 9.120e+00, 5.790e+00, -1.578e+02, -2.040e+01, 6.620e+01],
         [5.370e+00, 7.940e+00, 8.630e+00, -6.560e+01, 7.480e+01, -2.800e+01],
         [5.300e+00, -1.578e+02, -6.560e+01, 3.422e+03, 2.108e+03, -2.546e+03],
         [-1.690e+01, -2.040e+01, 7.480e+01, 8.150e+02, -1.255e+03, 5.650e+02],
         [9.500e+00, 6.620e+01, -2.800e+01, -1.684e+03, 1.340e+02, 4.720e+02]])
    assert_allclose(np.asarray(tf.translate_matrix_3to6(Min, r)), desired, rtol=1e-5)


def test_translate_matrix_6to6():
    Min = np.array([[0.57, 0.64, 0.88, 0.12, 0.34, 0.56],
                    [2.03, -13.02, 8.00, 0.78, 0.90, 0.12],
                    [1.11, -0.15, 0.10, 0.34, 0.56, 0.78],
                    [0.12, 0.78, 0.34, 0.90, 0.12, 0.34],
                    [0.34, 0.90, 0.56, 0.12, 0.34, 0.56],
                    [0.56, 0.12, 0.78, 0.34, 0.56, 0.78]])
    r = np.array([10.0, 20.0, 30.0])
    desired = np.array(
        [[5.70000e-01, 6.40000e-01, 8.80000e-01, -1.48000e+00, 8.64000e+00, -4.44000e+00],
         [2.03000e+00, -1.30200e+01, 8.00000e+00, 5.51380e+02, -1.82000e+01, -1.70680e+02],
         [1.11000e+00, -1.50000e-01, 1.00000e-01, 6.84000e+00, 3.28600e+01, -2.29200e+01],
         [-1.48000e+00, 5.51380e+02, 6.84000e+00, -1.64203e+04, 1.20352e+03, 4.66774e+03],
         [8.64000e+00, -1.82000e+01, 3.28600e+01, -1.28480e+02, -6.44600e+01, 9.87600e+01],
         [-4.44000e+00, -1.70680e+02, -2.29200e+01, 5.55574e+03, -3.45240e+02, -1.62722e+03]])
    assert_allclose(np.asarray(tf.translate_matrix_6to6(Min, r)), desired, rtol=1e-5)


def test_rotate_matrix_6():
    R = tf.rotation_matrix(0.1, 0.2, 0.3)
    Min = np.array([[0.57, 0.64, 0.88, 0.12, 0.34, 0.56],
                    [2.03, -13.02, 8.00, 0.78, 0.90, 0.12],
                    [1.11, -0.15, 0.10, 0.34, 0.56, 0.78],
                    [0.12, 0.78, 0.34, 0.90, 0.12, 0.34],
                    [0.34, 0.90, 0.56, 0.12, 0.34, 0.56],
                    [0.56, 0.12, 0.78, 0.34, 0.56, 0.78]])
    desired = np.array(
        [[-1.23327412, 4.08056795, -0.95870608, 0.06516703, 0.15206293, 0.66964386],
         [7.03270577, -11.42123791, 6.09625616, 0.51524892, 1.11098643, 0.18118973],
         [1.67312218, -1.16775529, 0.30451203, 0.34805446, 0.62871201, 0.62384654],
         [0.06516703, 0.51524892, 0.34805446, 0.86182628, 0.37858592, 0.16449501],
         [0.15206293, 1.11098643, 0.62871201, 0.37858592, 0.40719201, 0.55131878],
         [0.66964386, 0.18118973, 0.62384654, 0.16449501, 0.55131878, 0.75098172]])
    assert_allclose(np.asarray(tf.rotate_matrix_6(Min, R)), desired, rtol=1e-5)


def test_rot_frm_2_vect():
    R0 = tf.rotation_matrix(0.1, 0.2, 0.3)
    A = np.array([5.0, 0.0, 0.0])
    B = np.asarray(R0) @ A
    R = tf.rot_frm_2_vect(A, B)
    assert_allclose(B, np.asarray(R) @ A, rtol=1e-5)
    # parallel vectors -> identity
    assert_allclose(np.asarray(tf.rot_frm_2_vect(A, A)), np.eye(3), atol=1e-12)


def test_batched_transforms_match_loop(rng):
    """vmap semantics: batched kernels equal the per-item results."""
    Ms = rng.normal(size=(7, 3, 3))
    rs = rng.normal(size=(7, 3))
    batched = np.asarray(tf.translate_matrix_3to6(Ms, rs))
    for i in range(7):
        assert_allclose(batched[i], np.asarray(tf.translate_matrix_3to6(Ms[i], rs[i])),
                        rtol=1e-12)
    M6 = rng.normal(size=(5, 6, 6))
    r6 = rng.normal(size=(5, 3))
    b6 = np.asarray(tf.translate_matrix_6to6(M6, r6))
    for i in range(5):
        assert_allclose(b6[i], np.asarray(tf.translate_matrix_6to6(M6[i], r6[i])),
                        rtol=1e-12)
