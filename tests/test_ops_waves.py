"""Wave kinematics / spectra kernels vs reference analytic values
(reference: tests/test_helpers.py:26-69) plus batching/jit invariants."""
import jax
import jax.numpy as jnp
import numpy as np
from numpy.testing import assert_allclose

from raft_tpu.ops import spectra, waves


def test_wave_number():
    w = np.array([0.1, 0.25, 0.5, 0.75])
    desired = np.array([0.00233623, 0.0071452, 0.02548611, 0.05733945])
    k = np.asarray(waves.wave_number(w, 200.0))
    assert_allclose(k, desired, rtol=1e-5)
    # deep water limit: k -> w^2/g
    kd = np.asarray(waves.wave_number(np.array([2.0]), 5000.0))
    assert_allclose(kd, [2.0**2 / 9.81], rtol=1e-3)
    assert np.asarray(waves.wave_number(np.array([0.0]), 100.0))[0] == 0.0


def test_wave_kinematics():
    w = np.array([0.1, 0.25, 0.5, 0.75])
    zeta0 = np.array([0.2, 0.2, 0.2, 0.2], dtype=complex)
    beta, h = 30.0, 200.0   # beta interpreted in radians, matching reference test
    r = np.array([30.0, 45.0, -20.0])
    k = np.asarray(waves.wave_number(w, h))

    desired_u = np.array(
        [[0.00690971 + 0.00064489j, 0.00732697 + 0.00214361j,
          0.00488759 + 0.00787284j, -0.00480898 + 0.00555819j],
         [-0.04425901 - 0.00413072j, -0.04693167 - 0.01373052j,
          -0.03130665 - 0.05042812j, 0.03080313 - 0.03560204j],
         [-0.00166131 + 0.01780023j, -0.01192503 + 0.04076042j,
          -0.05102840 + 0.03167931j, -0.03603330 - 0.03117625j]])
    desired_ud = np.array(
        [[-0.0000644885 + 0.0006909710j, -0.0005359019 + 0.0018317440j,
          -0.0039364177 + 0.0024438000j, -0.0041686415 - 0.0036067400j],
         [0.0004130725 - 0.0044259010j, 0.0034326291 - 0.0117329200j,
          0.0252140594 - 0.0156533200j, 0.0267015296 + 0.0231023400j],
         [-0.0017800228 - 0.0001661310j, -0.0101901044 - 0.0029812600j,
          -0.0158396548 - 0.0255142000j, 0.0233821912 - 0.0270249700j]])
    desired_pDyn = np.array([1963.730340920 + 183.276331860j,
                             1703.156386190 + 498.282218140j,
                             637.171137130 + 1026.342526750j,
                             -417.980049950 + 483.098446900j])

    u, ud, pDyn = waves.wave_kinematics(zeta0, beta, w, k, h, r)
    assert_allclose(np.asarray(u), desired_u, rtol=1e-5)
    assert_allclose(np.asarray(ud), desired_ud, rtol=1e-5)
    assert_allclose(np.asarray(pDyn), desired_pDyn, rtol=1e-5)


def test_wave_kinematics_above_water_and_batched():
    w = np.array([0.3, 0.6])
    k = np.asarray(waves.wave_number(w, 100.0))
    zeta0 = np.array([1.0 + 0.5j, 0.3 - 0.2j])
    # node above the surface -> all zeros
    u, ud, pD = waves.wave_kinematics(zeta0, 0.2, w, k, 100.0, np.array([1.0, 2.0, 3.0]))
    assert np.all(np.asarray(u) == 0) and np.all(np.asarray(pD) == 0)
    # batched nodes give same result as per-node calls
    rs = np.array([[0.0, 0.0, -5.0], [10.0, -3.0, -50.0], [2.0, 2.0, 1.0]])
    ub, udb, pb = waves.wave_kinematics(zeta0, 0.2, w, k, 100.0, rs)
    for i in range(3):
        ui, udi, pi = waves.wave_kinematics(zeta0, 0.2, w, k, 100.0, rs[i])
        assert_allclose(np.asarray(ub)[i], np.asarray(ui), rtol=1e-12)
        assert_allclose(np.asarray(pb)[i], np.asarray(pi), rtol=1e-12)


def test_kinematics_from_motion():
    r = np.array([2.0, 2.0, 2.0])
    w = np.array([0.5, 0.75])
    Xi = np.array([[1, 2 + 1j], [0.1 + 0.2j, 0.3 + 0.4j], [0.5 + 0.6j, 0.7 + 0.8j],
                   [0.9 + 1.0j, 1.1 + 1.2j], [1.3 + 1.4j, 1.5 + 1.6j],
                   [1.7 + 1.8j, 1.9 + 2.0j]])
    desired = np.array([
        [[0.2 - 8.00000000e-01j, 1.2 + 2.00000000e-01j],
         [1.7 + 1.80000000e+00j, 1.9 + 2.00000000e+00j],
         [-0.3 - 2.00000000e-01j, -0.1 - 2.22044605e-16j]],
        [[4.00000000e-01 + 0.1j, -1.50000000e-01 + 0.9j],
         [-9.00000000e-01 + 0.85j, -1.50000000e+00 + 1.425j],
         [1.00000000e-01 - 0.15j, 1.66533454e-16 - 0.075j]],
        [[-0.05 + 2.0000000e-01j, -0.675 - 1.1250000e-01j],
         [-0.425 - 4.5000000e-01j, -1.06875 - 1.1250000e+00j],
         [0.075 + 5.0000000e-02j, 0.05625 + 1.2490009e-16j]]])
    dr, v, a = waves.kinematics_from_motion(r, Xi, w)
    assert_allclose(np.asarray(dr), desired[0], rtol=1e-5, atol=1e-12)
    assert_allclose(np.asarray(v), desired[1], rtol=1e-5, atol=1e-12)
    assert_allclose(np.asarray(a), desired[2], rtol=1e-5, atol=1e-12)


def test_jonswap_matches_reference_formula():
    ws = np.linspace(0.03, 2.5, 100)
    for Hs, Tp in [(6.0, 10.0), (2.0, 14.0), (9.0, 8.0)]:
        S = np.asarray(spectra.jonswap(ws, Hs, Tp))
        # re-derive with plain numpy (reference formula, helpers.py:606-663)
        TpOvrSqrtHs = Tp / np.sqrt(Hs)
        if TpOvrSqrtHs <= 3.6:
            Gamma = 5.0
        elif TpOvrSqrtHs >= 5.0:
            Gamma = 1.0
        else:
            Gamma = np.exp(5.75 - 1.15 * TpOvrSqrtHs)
        f = 0.5 / np.pi * ws
        fpOvrf4 = (Tp * f) ** -4.0
        C = 1.0 - 0.287 * np.log(Gamma)
        Sigma = 0.07 * (f <= 1.0 / Tp) + 0.09 * (f > 1.0 / Tp)
        Alpha = np.exp(-0.5 * ((f * Tp - 1.0) / Sigma) ** 2)
        S_ref = 0.5 / np.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f \
            * np.exp(-1.25 * fpOvrf4) * Gamma**Alpha
        assert_allclose(S, S_ref, rtol=1e-12)
    # spectrum integrates to ~ (Hs/4)^2 variance (sanity, coarse tolerance)
    ws_f = np.linspace(0.02, 4.0, 4000)
    S = np.asarray(spectra.jonswap(ws_f, 6.0, 10.0))
    m0 = np.trapezoid(S, ws_f)
    assert abs(np.sqrt(m0) - 6.0 / 4.0) / (6.0 / 4.0) < 0.05


def test_psd_rms_rao():
    rng = np.random.default_rng(0)
    xi = rng.normal(size=(3, 20)) + 1j * rng.normal(size=(3, 20))
    dw = 0.01
    assert_allclose(float(spectra.get_rms(xi)), np.sqrt(0.5 * np.sum(np.abs(xi) ** 2)),
                    rtol=1e-12)
    assert_allclose(np.asarray(spectra.get_psd(xi, dw, source_axis=0)),
                    np.sum(0.5 * np.abs(xi) ** 2 / dw, axis=0), rtol=1e-12)
    zeta = rng.normal(size=20) + 1j * rng.normal(size=20)
    zeta[5] = 0.0
    rao = np.asarray(spectra.get_rao(xi, zeta))
    assert np.all(rao[:, 5] == 0)
    assert_allclose(rao[:, 6], xi[:, 6] / zeta[6], rtol=1e-12)


def test_wave_kinematics_jits_and_vmaps():
    w = jnp.linspace(0.05, 2.0, 40)
    k = waves.wave_number(w, 150.0)
    zeta0 = jnp.ones(40, dtype=complex)
    rs = jnp.array([[0.0, 0.0, -z] for z in np.linspace(1, 80, 16)])
    f = jax.jit(lambda r: waves.wave_kinematics(zeta0, 0.0, w, k, 150.0, r))
    u, ud, pD = f(rs)
    assert u.shape == (16, 3, 40)
    assert pD.shape == (16, 40)
    assert bool(jnp.all(jnp.isfinite(u.real)))
