"""Differentiable co-design: implicit-diff solvers, batched descents,
and the optimize serve tenant (PR: ISSUE 14).

Covers the ISSUE's gradient-correctness satellite head on:

- finite-difference parity (<= 1e-5 rel) for ∂std/∂design on the small
  cylinder through the FULL implicit pipeline (statics Newton + drag
  fixed point + impedance custom_vjp);
- custom_vjp-vs-unrolled-autodiff agreement on a short fixed point;
- adjoint dispatch facts (``last_dispatch()["adjoint"]``) and the
  impedance custom_vjp's machine-precision match to native autodiff;
- batched-descent lane isolation (one poisoned lane never stalls the
  batch) and the exec-cache warm hit for ``fn="optimize"``;
- warm_start x mesh composition parity on virtual devices (PR 12's
  open satellite) and statics Newton warm-start seeding in
  ``Model.analyzeCases`` (ROADMAP item 5's open satellite);
- the optimize serve tenant's WAL journaling and replay idempotence
  (stubbed descents — the service machinery, not the physics).

The physics fixtures ride the 2-frequency-bin cylinder so the module
stays targeted-runnable on slow hosts; nothing here is reached by the
alphabetical tier-1 window.
"""
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu import errors
from raft_tpu.ops import linalg
from raft_tpu.parallel import optimize as opt
from raft_tpu.parallel.variants import make_variant_solver
from raft_tpu.serve.soak import build_fowt


@pytest.fixture(scope="module")
def cyl():
    return build_fowt("Vertical_cylinder", 0.1, 0.9, 0.4)   # 2 bins


@pytest.fixture(scope="module")
def cyl_space(cyl):
    return opt.DesignSpace(cyl, {"d_scale": (0.9, 1.1),
                                 "moor_L": (0.95, 1.05)})


# ---------------------------------------------------------------------------
# impedance custom_vjp: parity with native autodiff + adjoint facts
# ---------------------------------------------------------------------------

def _impedance_ref(w, M, B, C, F):
    Z = (-w ** 2 * M + 1j * w * B + C[..., None]).astype(complex)
    X = linalg.solve_complex(jnp.moveaxis(Z, -1, -3),
                             jnp.moveaxis(F, -1, -2))
    return jnp.moveaxis(X, -2, -1)


def test_impedance_custom_vjp_matches_native_autodiff():
    rng = np.random.default_rng(7)
    n, nw, nc = 3, 4, 2
    w = jnp.asarray(rng.uniform(0.5, 2.0, nw))
    M = jnp.asarray(rng.normal(size=(nc, n, n, nw)))
    B = jnp.asarray(rng.normal(size=(nc, n, n, nw)))
    C = jnp.asarray(rng.normal(size=(nc, n, n)))
    F = jnp.asarray(rng.normal(size=(nc, n, nw))
                    + 1j * rng.normal(size=(nc, n, nw)))

    def obj(fn):
        return lambda w, *a: jnp.sum(jnp.abs(fn(w, *a)) ** 2)

    g_custom = jax.grad(obj(linalg.impedance_solve),
                        argnums=(0, 1, 2, 3, 4))(w, M, B, C, F)
    g_native = jax.grad(obj(_impedance_ref),
                        argnums=(0, 1, 2, 3, 4))(w, M, B, C, F)
    for gc, gn in zip(g_custom, g_native):
        ref = float(jnp.max(jnp.abs(gn)))
        assert float(jnp.max(jnp.abs(gc - gn))) <= 1e-12 * max(ref, 1.0)
    # primal untouched by the custom_vjp wrapper
    np.testing.assert_array_equal(
        np.asarray(linalg.impedance_solve(w, M, B, C, F)),
        np.asarray(_impedance_ref(w, M, B, C, F)))


def test_adjoint_dispatch_facts_recorded():
    rng = np.random.default_rng(8)
    n, nw = 2, 3
    w = jnp.asarray(rng.uniform(0.5, 2.0, nw))
    M = jnp.asarray(rng.normal(size=(n, n, nw)))
    B = jnp.asarray(rng.normal(size=(n, n, nw)))
    C = jnp.asarray(rng.normal(size=(n, n)))
    F = jnp.asarray(rng.normal(size=(n, nw)) + 0j)
    jax.grad(lambda F: jnp.sum(jnp.abs(
        linalg.impedance_solve(w, M, B, C, F)) ** 2))(F)
    d = linalg.last_dispatch()
    # the LAST dispatch of a reverse pass is the adjoint solve, riding
    # the same backend ladder with the adjoint fact set
    assert d.get("adjoint") is True
    assert d["backend"] in ("lu", "jnp_gj", "pallas_gj", "pallas_fused")
    # a fresh forward dispatch clears the adjoint fact (cleared, not
    # merged — same contract as the precision facts)
    linalg.impedance_solve(w, M, B, C, F)
    assert "adjoint" not in linalg.last_dispatch()


# ---------------------------------------------------------------------------
# gradient correctness on the small cylinder
# ---------------------------------------------------------------------------

def test_fd_parity_std_gradient_small_cylinder(cyl, cyl_space):
    """∂(weighted RAO std)/∂(hull diameter, mooring length) from the
    implicit pipeline matches central finite differences at <= 1e-5
    relative — the ISSUE acceptance bound."""
    obj = opt.make_design_objective(
        cyl, cyl_space, {"metric": "std", "Hs": 5.0, "Tp": 9.0},
        nIter=40, tol=1e-10)
    x = jnp.ones(2)
    # grad_guarded = the taxonomy-guarded value_and_grad (a non-finite
    # adjoint would raise NonFiniteResult with phase="adjoint")
    v, g = opt.grad_guarded(obj)(x)
    assert np.isfinite(float(v)) and np.all(np.isfinite(np.asarray(g)))
    eps = 1e-6
    for i in range(2):
        fd = float((obj(x.at[i].add(eps)) - obj(x.at[i].add(-eps)))
                   / (2 * eps))
        rel = abs(float(g[i]) - fd) / max(abs(fd), 1e-30)
        assert rel <= 1e-5, (i, float(g[i]), fd, rel)


def test_custom_vjp_matches_unrolled_autodiff(cyl, cyl_space):
    """Implicit differentiation of the drag fixed point agrees with
    differentiating a (well-converged) unrolled iteration."""
    solver = make_variant_solver(cyl, Hs=5.0, Tp=9.0, beta=0.0,
                                 ballast=False, nIter=30, tol=1e-9,
                                 implicit_diff=True)
    nw = len(cyl.w)
    x = jnp.ones(2)

    def f_implicit(x):
        st = solver.setup(cyl_space.to_theta(x))
        Xi0 = jnp.zeros((6, nw), dtype=complex) + 0.1
        Xi = opt.fixed_point_implicit(
            lambda z: solver.drag_step(st, z), Xi0, nIter=30, tol=1e-9)
        return jnp.sum(opt._abs2(Xi))

    def f_unrolled(x):
        st = solver.setup(cyl_space.to_theta(x))
        Xi = jnp.zeros((6, nw), dtype=complex) + 0.1
        for _ in range(30):
            Xi = 0.2 * Xi + 0.8 * solver.drag_step(st, Xi)
        return jnp.sum(opt._abs2(Xi))

    gi = np.asarray(jax.grad(f_implicit)(x))
    gu = np.asarray(jax.grad(f_unrolled)(x))
    assert np.all(np.isfinite(gi)) and np.all(np.isfinite(gu))
    np.testing.assert_allclose(gi, gu, rtol=1e-5)


def test_objective_primal_matches_sweep_metrics(cyl, cyl_space):
    """The grad-safe objective layer matches the sweep path's metrics
    to one ulp (safe_rms accumulates |z|² as re²+im² — same value up
    to the last bit of ``abs``'s internal rounding) and is EXACT at
    the zero rows where the gradients differ (0 vs NaN)."""
    from raft_tpu.ops.spectra import get_rms

    rng = np.random.default_rng(3)
    Xi = jnp.asarray(rng.normal(size=(6, 5))
                     + 1j * rng.normal(size=(6, 5)))
    Xi = Xi.at[1].set(0.0)       # a symmetric DOF's exact-zero row
    a = np.asarray(opt.safe_rms(Xi, axis=-1))
    b = np.asarray(get_rms(Xi, axis=-1))
    np.testing.assert_allclose(a, b, rtol=1e-15)
    assert a[1] == b[1] == 0.0
    # del proxy: finite gradient at zero-response rows
    w = jnp.linspace(0.3, 1.5, 5)
    g = jax.grad(lambda z: jnp.sum(opt.del_proxy(z, w)))(Xi)
    assert bool(jnp.all(jnp.isfinite(opt._abs2(g))))


# ---------------------------------------------------------------------------
# design spaces / request specs (pure validation — fast)
# ---------------------------------------------------------------------------

def test_design_space_validation(cyl):
    with pytest.raises(errors.ModelConfigError):
        opt.DesignSpace(cyl, {})
    with pytest.raises(errors.ModelConfigError):
        opt.DesignSpace(cyl, {"nope": (0.9, 1.1)})
    with pytest.raises(errors.ModelConfigError):
        opt.DesignSpace(cyl, {"d_scale": (1.1, 0.9)})
    space = opt.DesignSpace(cyl, {"ballast": (0.8, 1.2),
                                  "moor_EA": (0.9, 1.1)})
    assert space.names == ["ballast", "moor_EA"]
    theta = space.to_theta(jnp.asarray([1.1, 1.05]))
    assert "rho_fill" in theta and "moor_EA" in theta
    fp = space.fingerprint()
    assert fp["names"] == ["ballast", "moor_EA"]
    x0 = space.sample(5, seed=1)
    assert x0.shape == (5, 2)
    assert np.all(x0 >= np.asarray(space.lower) - 1e-12)
    assert np.all(x0 <= np.asarray(space.upper) + 1e-12)


def test_normalize_request_validation():
    ok = opt.normalize_request(
        {"bounds": {"d_scale": [0.9, 1.1]}, "nlanes": 4})
    assert ok["bounds"] == {"d_scale": [0.9, 1.1]}
    assert ok["objective"]["metric"] == "std"
    assert list(ok) == sorted(ok)        # canonical ordering
    for bad in (
            "not a dict",
            {"bounds": None},
            {"bounds": {"nope": [0.9, 1.1]}},
            {"bounds": {"d_scale": [1.1, 0.9]}},
            {"bounds": {"d_scale": [0.9, 1.1]}, "method": "sgd"},
            {"bounds": {"d_scale": [0.9, 1.1]}, "nlanes": 0},
            {"bounds": {"d_scale": [0.9, 1.1]}, "lr": -1.0},
            {"bounds": {"d_scale": [0.9, 1.1]}, "surprise": 1},
            {"bounds": {"d_scale": [0.9, 1.1]},
             "objective": {"metric": "nope"}},
            {"bounds": {"d_scale": [0.9, 1.1]},
             "objective": {"dof": "surge"}},
            {"bounds": {"d_scale": [0.9, 1.1]},
             "objective": {"Hs": "abc"}},
            {"bounds": {"d_scale": [0.9, 1.1]},
             "objective": {"Tp": -1.0}},
            {"bounds": {"d_scale": [0.9, 1.1]},
             "objective": {"weights": [1.0, 2.0]}},
            # nIter is the Python-unrolled trace-size knob: hard-capped
            {"bounds": {"d_scale": [0.9, 1.1]}, "nIter": 10_000},
    ):
        with pytest.raises(errors.ModelConfigError):
            opt.normalize_request(bad)
    with pytest.raises(errors.ModelConfigError):
        opt.normalize_request({"bounds": {"d_scale": [0.9, 1.1]},
                               "nlanes": 64}, lanes_max=32)
    with pytest.raises(errors.ModelConfigError):
        opt.normalize_request({"bounds": {"d_scale": [0.9, 1.1]},
                               "steps": 500}, steps_max=200)


def test_optimize_digest_stable_and_canonical():
    from raft_tpu.serve import journal as wal

    a = opt.normalize_request({"bounds": {"d_scale": [0.9, 1.1],
                                          "moor_L": [0.98, 1.02]}})
    b = opt.normalize_request({"bounds": {"moor_L": [0.98, 1.02],
                                          "d_scale": [0.9, 1.1]}})
    assert wal.optimize_digest(a, "t1") == wal.optimize_digest(b, "t1")
    assert wal.optimize_digest(a, "t1") != wal.optimize_digest(a, "t2")


# ---------------------------------------------------------------------------
# batched descent: lane isolation + exec-cache identity
# ---------------------------------------------------------------------------

def test_batched_descent_lane_isolation(cyl, cyl_space, tmp_path):
    """One poisoned lane (NaN start) is frozen and counted; the healthy
    lanes descend to finite objectives — the batch never stalls."""
    x0 = np.array([[1.0, 1.0], [np.nan, 1.0], [0.95, 1.02]])
    res = opt.optimize_designs(
        cyl, cyl_space, {"metric": "std", "Hs": 5.0, "Tp": 9.0},
        x0=x0, steps=3, lr=0.03, method="adam", nIter=5, tol=1e-3,
        adjoint_iters=6)
    assert list(res["nonfinite"]) == [False, True, False]
    assert np.all(np.isfinite(res["objective"][[0, 2]]))
    assert not np.isfinite(res["objective"][1])
    assert res["lane_best"] in (0, 2)
    prov = res["provenance"]
    assert prov["grad_nonfinite"] == 1
    assert len(prov["objective"]) >= 1       # canonical spec recorded
    # all-poisoned is a typed adjoint failure
    with pytest.raises(errors.NonFiniteResult) as ei:
        opt.optimize_designs(
            cyl, cyl_space, {"metric": "std", "Hs": 5.0, "Tp": 9.0},
            x0=np.full((2, 2), np.nan), steps=2, nIter=4, tol=1e-3,
            adjoint_iters=4)
    assert ei.value.phase == "adjoint"


def test_optimize_exec_cache_warm_hit(cyl, cyl_space, tmp_path,
                                      monkeypatch):
    """fn="optimize" exec-cache identity: first descent stores, the
    repeat deserializes (state miss -> hit) and reproduces bitwise; a
    different objective/bounds fingerprint misses."""
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path / "x"))
    kw = dict(nlanes=2, steps=2, lr=0.03, method="adam", seed=5,
              nIter=4, tol=1e-3, adjoint_iters=4)
    spec = {"metric": "std", "Hs": 5.0, "Tp": 9.0}
    r1 = opt.optimize_designs(cyl, cyl_space, spec, **kw)
    assert r1["provenance"]["exec_cache"] == "miss"
    r2 = opt.optimize_designs(cyl, cyl_space, spec, **kw)
    assert r2["provenance"]["exec_cache"] == "hit"
    np.testing.assert_array_equal(r1["x"], r2["x"])
    np.testing.assert_array_equal(r1["objective"], r2["objective"])
    # objective identity forks the key
    r3 = opt.optimize_designs(cyl, cyl_space,
                              {"metric": "offset", "Hs": 5.0,
                               "Tp": 9.0}, **kw)
    assert r3["provenance"]["exec_cache"] == "miss"


# ---------------------------------------------------------------------------
# warm_start x mesh composition (PR 12's open satellite)
# ---------------------------------------------------------------------------

def test_warm_start_composes_with_mesh(cyl):
    """A meshed warm_start runner places its Xi0 seed via the partition
    rules (XI_SPEC) and reproduces the unmeshed warm runner — cold-fill
    and explicitly seeded calls alike — on virtual devices."""
    from raft_tpu.parallel.partition import make_mesh
    from raft_tpu.parallel.sweep import make_batch_runner
    from raft_tpu.serve.config import ServeConfig

    # the ServeConfig gate that used to reject warm_start+mesh is gone
    mesh = make_mesh((2,), ("cases",))
    cfg = ServeConfig(store_dir="/tmp/s", warm_start=True, mesh=mesh)
    assert cfg.warm_start and cfg.mesh is mesh

    kw = dict(nIter=6, tol=1e-3, warmup=False)
    plain = make_batch_runner(cyl, 2, warm_start=True, **kw)
    meshed = make_batch_runner(cyl, 2, warm_start=True, mesh=mesh, **kw)
    Hs = np.array([1.5, 2.5])
    Tp = np.array([7.0, 9.0])
    beta = np.zeros(2)
    cold_p = plain(Hs, Tp, beta)
    cold_m = meshed(Hs, Tp, beta)
    np.testing.assert_allclose(np.asarray(cold_m["std"]),
                               np.asarray(cold_p["std"]),
                               rtol=0, atol=1e-12)
    np.testing.assert_array_equal(np.asarray(cold_m["iters"]),
                                  np.asarray(cold_p["iters"]))
    # explicit seed (a converged response) through the sharded placement
    seed = np.asarray(cold_p["Xi"])
    warm_p = plain(Hs, Tp, beta, Xi0=seed)
    warm_m = meshed(Hs, Tp, beta, Xi0=seed)
    np.testing.assert_allclose(np.asarray(warm_m["std"]),
                               np.asarray(warm_p["std"]),
                               rtol=0, atol=1e-12)
    np.testing.assert_array_equal(np.asarray(warm_m["iters"]),
                                  np.asarray(warm_p["iters"]))
    # seeding saves iterations over the cold fill on both layouts
    assert int(np.max(np.asarray(warm_m["iters"]))) <= \
        int(np.max(np.asarray(cold_m["iters"])))


# ---------------------------------------------------------------------------
# statics Newton warm-start seeding (ROADMAP item 5's open satellite)
# ---------------------------------------------------------------------------

def _cyl_design_cases(n_cases):
    from raft_tpu.io.designs import load_design

    design = load_design("Vertical_cylinder")
    design.setdefault("settings", {})
    design["settings"]["min_freq"] = 0.1
    design["settings"]["max_freq"] = 0.5
    data = design["cases"]["data"]
    design["cases"]["data"] = [list(data[0]) for _ in range(n_cases)]
    return design


def test_statics_warm_start_seeding():
    from raft_tpu.model import Model

    cold = Model(_cyl_design_cases(3))
    cold.analyzeCases()
    assert cold.last_manifest.extra.get("statics_warm") is None
    warm = Model(_cyl_design_cases(3))
    warm.analyzeCases(warm_statics=True)
    facts = warm.last_manifest.extra["statics_warm"]
    # cases 1 and 2 were seeded from the previous converged pose (the
    # guard may cold re-solve, but every seeded case is counted)
    assert facts["seeded"] + facts["rejected"] == 2
    # the equilibrium itself is unchanged within the Newton tolerance
    np.testing.assert_allclose(
        np.asarray(warm.results["mean_offsets"]),
        np.asarray(cold.results["mean_offsets"]), atol=1e-4)
    # seeding state never leaks past the run
    assert warm._statics_warm is False and warm._statics_seed is None


# ---------------------------------------------------------------------------
# optimize serve tenant: WAL journaling + replay idempotence (stubbed)
# ---------------------------------------------------------------------------

def _stub_descent(calls):
    def stub(base, space, objective=None, *, nlanes=32, steps=30,
             method="adam", lr=0.02, gtol=1e-4, seed=0, nIter=10,
             tol=0.01, **kw):
        calls.append({"nlanes": nlanes, "steps": steps})
        L = int(nlanes)
        return {
            "x": np.ones((L, space.ndim)),
            "objective": np.full(L, 1.5), "grad_norm": np.zeros(L),
            "converged": np.ones(L, bool),
            "nonfinite": np.zeros(L, bool),
            "iters": np.full(L, steps, np.int32),
            "obj_trace": np.full((int(steps), L), 1.5),
            "x_best": np.ones(space.ndim), "f_best": 1.5,
            "lane_best": 0,
            "design": {n: 1.0 for n in space.names},
            "provenance": {"method": method, "steps": int(steps),
                           "iterations": int(steps),
                           "grad_norm_best": 0.0, "grad_nonfinite": 0,
                           "converged": L, "wall_s": 0.01,
                           "objective": objective or {},
                           "exec_cache": "disabled"},
        }
    return stub


@pytest.fixture()
def opt_service(cyl, tmp_path, monkeypatch):
    from raft_tpu.serve import SweepService
    from raft_tpu.serve.config import ServeConfig

    calls = []
    monkeypatch.setattr(opt, "optimize_designs", _stub_descent(calls))
    cfg = ServeConfig(journal_dir=str(tmp_path / "wal"),
                      deadline_s=30.0)
    svc = SweepService(cyl, cfg)
    yield svc, calls, str(tmp_path / "wal")
    svc.stop(drain=False, timeout=5.0)


SPEC = {"bounds": {"d_scale": [0.9, 1.1]}, "nlanes": 3, "steps": 4}


def test_submit_optimize_journaled_delivery(opt_service):
    svc, calls, wal_dir = opt_service
    t = svc.submit_optimize(dict(SPEC))
    res = t.result(10.0)
    assert res.ok and res.mode == "optimize"
    assert res.extra["design"] == {"d_scale": 1.0}
    assert res.extra["f_best"] == 1.5
    prov = res.extra["provenance"]
    assert prov["iterations"] == 4
    assert len(prov["objective_trace"]) == 4
    assert prov["grad_norm_best"] == 0.0
    assert len(calls) == 1
    # duplicate: dedupe from the delivered index, no second descent
    r2 = svc.submit_optimize(dict(SPEC)).result(10.0)
    assert r2.source == "deduped" and r2.digest == res.digest
    assert len(calls) == 1
    # fetchable by digest like any result
    assert svc.fetch(res.digest).extra["f_best"] == 1.5
    # WAL carries the spec on admit and the payload on complete
    from raft_tpu.serve import journal as wal
    state = wal.replay(wal_dir)
    admits = [r for r in state["admitted"].values() if r.get("opt")]
    assert admits and admits[0]["opt"]["bounds"] == SPEC["bounds"]
    comp = state["completed"][admits[0]["seq"]]
    assert comp["mode"] == "optimize"
    assert comp["extra"]["design"] == {"d_scale": 1.0}


def test_optimize_replay_idempotent(cyl, tmp_path, monkeypatch):
    """An accepted-but-unfinished optimization replays (re-runs as
    submitted); a completed one re-delivers WITHOUT a descent; the
    second replay sees all-terminal."""
    from raft_tpu.serve import SweepService
    from raft_tpu.serve import journal as wal
    from raft_tpu.serve.config import ServeConfig

    calls = []
    monkeypatch.setattr(opt, "optimize_designs", _stub_descent(calls))
    src = str(tmp_path / "crashed")
    spec = opt.normalize_request(dict(SPEC))
    rdigest = wal.optimize_digest(spec, "default")
    j = wal.RequestJournal(src)
    j.record_admit(0, "opt0-dead", rdigest, 0.0, 1.0, 0.0, 30.0,
                   "default", opt=spec)
    j.close()
    cfg = ServeConfig(journal_dir=str(tmp_path / "succ"),
                      deadline_s=30.0)
    svc = SweepService(cyl, cfg)
    try:
        info = svc.recover(src)
        assert info["replayed"] == 1
        res = info["tickets"][0].result(10.0)
        assert res.ok and res.mode == "optimize"
        assert res.source == "replayed"
        assert res.extra["f_best"] == 1.5
        assert len(calls) == 1
    finally:
        svc.stop(drain=False, timeout=5.0)
    # successor's own WAL is now terminal for that request: a THIRD
    # life re-delivers without any descent
    calls.clear()
    svc2 = SweepService(cyl, cfg)
    try:
        info2 = svc2.recover()
        assert info2["recovered"] >= 1 and info2["replayed"] == 0
        got = svc2.fetch_rdigest(rdigest)
        assert got is not None and got.extra["f_best"] == 1.5
        assert calls == []
    finally:
        svc2.stop(drain=False, timeout=5.0)


def test_submit_optimize_rejects_typed(opt_service):
    svc, _calls, _ = opt_service
    with pytest.raises(errors.ModelConfigError):
        svc.submit_optimize({"bounds": {"nope": [0.9, 1.1]}})
    with pytest.raises(errors.ModelConfigError):
        svc.submit_optimize({"bounds": {"d_scale": [0.9, 1.1]},
                             "nlanes": 10_000})
    with pytest.raises(errors.ModelConfigError):
        svc.submit_optimize(dict(SPEC), tenant="ghost")


def test_optimize_module_lints_clean_under_solve_rules():
    """parallel/optimize.py is an RTL004 solve module (raft_tpu/parallel
    is in the configured solve-modules): typed raises only, and the
    module lints clean under the full rule set."""
    import subprocess
    import sys

    from tools.raftlint.config import load_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(repo)
    assert any("raft_tpu/parallel" in m
               for m in cfg.options("rtl004").get("solve-modules", []))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raftlint",
         "raft_tpu/parallel/optimize.py"],
        capture_output=True, text=True, cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# trend facts + SLO rule
# ---------------------------------------------------------------------------

def test_optimize_trend_facts_and_slo_rule(tmp_path):
    from raft_tpu.obs import trendstore

    doc = {"kind": "bench_optimize", "config": {},
           "extra": {"bench_optimize": {
               "descents_per_min": 12.0, "adjoint_s_per_step": 2.0,
               "speedup_vs_dense_sweep": 3.5, "dense_points": 25,
               "grad_nonfinite_ratio": 0.0, "argmin_match": 1,
               "f_best": 2.2, "objective_gap": -1e-6,
               "design_gap_max_spacing": 0.4, "method": "adam",
               "exec_cache": "hit"}}}
    facts = trendstore.facts_from_manifest(doc)
    assert facts["optimize_descents_per_min"] == 12.0
    assert facts["optimize_speedup_vs_dense_sweep"] == 3.5
    assert facts["optimize_grad_nonfinite_ratio"] == 0.0
    assert facts["optimize_argmin_match"] == 1
    assert facts["optimize_exec_cache_warm"] == 1
    rules = {r["name"] for r in trendstore.DEFAULT_SLO_RULES}
    assert "optimize_grad_nonfinite_ratio" in rules
    # rule evaluation: a clean row passes, a poisoned row violates
    def doc_for(run_id, ratio):
        bench = dict(doc["extra"]["bench_optimize"],
                     grad_nonfinite_ratio=ratio)
        return {"schema": "raft_tpu.run_manifest/v1", "run_id": run_id,
                "kind": "bench_optimize", "status": "ok",
                "started_at": "2026-08-04T10:00:00+00:00",
                "duration_s": 10.0, "environment": {}, "config": {},
                "extra": {"bench_optimize": bench}}

    rule = [r for r in trendstore.DEFAULT_SLO_RULES
            if r["name"] == "optimize_grad_nonfinite_ratio"]
    store = trendstore.TrendStore(str(tmp_path / "trend.sqlite"))
    store.append(doc_for("r1", 0.0))
    verdict = trendstore.evaluate_slo(store.rows(), rule)
    assert verdict["ok"] and not verdict["results"][0]["skipped"]
    store.append(doc_for("r2", 0.25))
    verdict = trendstore.evaluate_slo(store.rows(), rule)
    assert verdict["ok"] is False          # max over window sees 0.25


def test_optimize_manifest_facts_from_run(cyl, cyl_space):
    """optimize_designs' own manifest lands descent facts the trend
    store extracts (the serve-tenant path gets trended for free)."""
    from raft_tpu.obs import trendstore

    res = opt.optimize_designs(
        cyl, cyl_space, {"metric": "std", "Hs": 5.0, "Tp": 9.0},
        nlanes=2, steps=2, lr=0.03, nIter=4, tol=1e-3,
        adjoint_iters=4, seed=9)
    assert res["provenance"]["grad_nonfinite"] == 0
    doc = {"kind": "optimize", "config": {},
           "extra": {"optimize": {
               "nlanes": 2, "steps": 2, "grad_nonfinite_ratio": 0.0,
               "descents_per_min": 1.0, "f_best": res["f_best"],
               "method": "adam", "exec_cache": "disabled"}}}
    facts = trendstore.facts_from_manifest(doc)
    assert facts["optimize_grad_nonfinite_ratio"] == 0.0
    assert facts["optimize_nlanes"] == 2
