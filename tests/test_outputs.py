"""Observability + misc analysis utilities: parametric case builder,
WAMIT .2 reader, stress PSDs, response export, plots, timing registry.

Reference analogs: helpers.py:966-1272, raft_model.py:315-341 (stats
table), :1194-1306 (plotResponses/saveResponses), :1333-1431 (plots).
"""
import os

import matplotlib
matplotlib.use("Agg")

import numpy as np
import pytest
import yaml

from raft_tpu.model import Model
from raft_tpu.utils.analysis import (adjust_mooring, clean_raft_dict,
                                     get_sigma_x_psd,
                                     parametric_analysis_builder,
                                     read_wamit_p2,
                                     retrieve_axis_par_analysis)
from raft_tpu.utils.profiling import (print_timing_report, timed,
                                      timing_report)


def test_parametric_analysis_builder():
    design = dict(
        parametricAnalysis=dict(windSpeedIncrement=2.0, numWSIncrements=3),
        cases=dict(keys=["wind_speed", "wave_height"], data=[[8.0, 2.0]]))
    out = parametric_analysis_builder(design, "windSpeed", start_value=6.0)
    data = out["cases"]["data"]
    assert [row[0] for row in data] == [6.0, 8.0, 10.0, 12.0]
    assert all(row[1] == 2.0 for row in data)

    # floaterRotation sweeps heading keys in lockstep
    design = dict(
        parametricAnalysis=dict(rotationAngle=30.0, numRotations=2),
        cases=dict(keys=["wind_speed", "wind_heading", "wave_heading"],
                   data=[[10.0, 0.0, 0.0]]))
    out = parametric_analysis_builder(design, "floaterRotation")
    data = out["cases"]["data"]
    assert [row[1] for row in data] == [0.0, 30.0, 60.0]
    assert [row[2] for row in data] == [0.0, 30.0, 60.0]

    # unknown type / disabled: no-op
    before = [list(r) for r in data]
    parametric_analysis_builder(out, "nope")
    assert out["cases"]["data"] == before

    xaxis, xlabel, _title = retrieve_axis_par_analysis(
        0, dict(zip(out["cases"]["keys"], data[1])), "windSpeed", [])
    assert xaxis == [10.0] and "Wind Speed" in xlabel


def test_read_wamit_p2(tmp_path):
    # synthetic .2 file: 2 periods x 2 headings x 6 dof
    path = tmp_path / "drift.2"
    rows = []
    for T in (10.0, 5.0):
        for head in (0.0, 90.0):
            for i in range(1, 7):
                re, im = i * T, -i * head / 90.0
                rows.append(f"{T} {head} {i} {np.hypot(re, im)} 0.0 {re} {im}")
    path.write_text("\n".join(rows) + "\n")
    out = read_wamit_p2(str(path), rho=1025.0, L=1.0, g=9.81)
    assert out["surge"].shape == (2, 2)
    # dimensionalization: rho*g*L^2 for translations, L^3 rotations
    assert out["surge"][0, 0] == pytest.approx(1025 * 9.81 * 5.0)  # T sorted asc
    assert out["yaw"][1, 1] == pytest.approx(1025 * 9.81 * (6 * 10 - 6j),
                                             rel=1e-12)


def test_get_sigma_x_psd():
    w = np.arange(0.1, 2.0, 0.1)
    TBFA = (1e6 + 0j) * np.ones_like(w)
    TBSS = np.zeros_like(w)
    psd, a_mesh, f_mesh = get_sigma_x_psd(TBFA, TBSS, w, d=10.0,
                                          thickness=0.083)
    assert psd.shape == (len(w), 50)
    # peak stress at theta=0 (pure fore-aft bending), zero at 90 deg
    Izz = np.pi / 8 * 0.083 * 1000.0
    sigma0 = 1e6 * 5.0 / Izz / 1e6
    expect = 0.5 * sigma0**2 / 0.1
    assert psd[0, 0] == pytest.approx(expect, rel=1e-6)
    i90 = np.argmin(np.abs(a_mesh[0] - np.pi / 2))
    assert psd[0, i90] < 5e-3 * psd[0, 0]   # grid point nearest 90 deg


def test_adjust_mooring_roundtrip():
    from raft_tpu.models import mooring as mr
    design = yaml.safe_load(open("/root/reference/designs/OC3spar.yaml"))
    ms = mr.parse_mooring(design["mooring"])
    ms2 = __import__("dataclasses").replace(ms, L=np.asarray(ms.L) + 25.0)
    out = adjust_mooring(ms2, design)
    for i, ln in enumerate(out["mooring"]["lines"]):
        assert ln["length"] == pytest.approx(float(np.asarray(ms.L)[i]) + 25.0)
    clean = clean_raft_dict(out)
    yaml.safe_dump(clean)      # numpy fully stripped -> dumps fine


def test_timing_registry():
    timing_report(reset=True)
    with timed("unit_test_section"):
        pass
    with timed("unit_test_section"):
        pass
    rep = timing_report()
    assert rep["unit_test_section"][1] == 2
    print_timing_report()      # smoke


@pytest.fixture(scope="module")
def small_model():
    design = yaml.safe_load(open("/root/reference/designs/OC3spar.yaml"))
    design["cases"]["data"] = [design["cases"]["data"][1]]   # parked case
    design["settings"]["max_freq"] = 0.2
    m = Model(design)
    m.analyzeUnloaded()
    m.analyzeCases(display=1)
    # the autouse obs-isolation fixture resets the span aggregate around
    # every test — capture the timing view now, at fixture time
    m.timing_at_fixture = timing_report()
    return m


def test_stats_table_printed(small_model, capsys):
    small_model._print_stats_table(0, 0)
    out = capsys.readouterr().out
    assert "Statistics" in out and "surge (m)" in out and "pitch (deg)" in out


def test_save_responses(small_model, tmp_path):
    files = small_model.saveResponses(str(tmp_path / "resp"))
    assert len(files) == 1
    lines = open(files[0]).read().splitlines()
    assert "surge_PSD" in lines[0] and "Mbase_PSD" in lines[0]
    assert len(lines) == 1 + small_model.nw
    first = [float(x) for x in lines[1].split()]
    assert first[0] == pytest.approx(small_model.w[0], abs=1e-4)


def test_plots(small_model, tmp_path):
    fig, ax = small_model.plot()
    fig.savefig(tmp_path / "sys3d.png")
    fig2, _ = small_model.plot2d()
    fig2.savefig(tmp_path / "sys2d.png")
    fig3, axes = small_model.plotResponses()
    fig3.savefig(tmp_path / "psd.png")
    assert (tmp_path / "sys3d.png").stat().st_size > 1000
    assert (tmp_path / "psd.png").stat().st_size > 1000
    import matplotlib.pyplot as plt
    plt.close("all")

    # timing registry was fed by analyzeCases (captured at fixture time;
    # the autouse obs reset clears the live aggregate between tests)
    rep = small_model.timing_at_fixture
    assert "solveDynamics" in rep and rep["solveDynamics"][1] >= 1


def test_convert_iea_turbine_yaml(tmp_path):
    """IEA-ontology -> RAFT turbine dict conversion on a synthetic minimal
    ontology (reference: helpers.py:777-930; no ontology file is vendored
    with the reference, so the schema subset it reads is synthesized)."""
    from raft_tpu.utils.analysis import convert_iea_turbine_yaml

    lin = {"grid": [0.0, 1.0]}
    wt = {
        "assembly": {"number_of_blades": 3, "rotor_diameter": 0.0,
                     "hub_height": 150.0},
        "components": {
            "hub": {"diameter": 8.0, "cone_angle": np.deg2rad(4.0)},
            "nacelle": {"drivetrain": {"uptilt": np.deg2rad(6.0),
                                       "overhang": 12.0,
                                       "distance_tt_hub": 5.0}},
            "tower": {"outer_shape_bem": {"reference_axis": {
                "z": {"grid": [0, 1], "values": [0.0, 145.0]}}}},
            "blade": {"outer_shape_bem": {
                "reference_axis": {
                    "x": {**lin, "values": [0.0, -4.0]},
                    "y": {**lin, "values": [0.0, 0.5]},
                    "z": {**lin, "values": [0.0, 116.0]},
                },
                "chord": {**lin, "values": [5.0, 1.0]},
                "twist": {**lin, "values": [np.deg2rad(15.0), 0.0]},
                "airfoil_position": {"grid": [0.0, 1.0],
                                     "labels": ["root", "tip"]},
            }},
        },
        "environment": {"air_density": 1.225, "air_dyn_viscosity": 1.81e-5,
                        "shear_exp": 0.12},
        "airfoils": [
            {"name": "root", "relative_thickness": 1.0, "polars": [{
                "c_l": {"grid": [-np.pi, 0.0, np.pi], "values": [0, 0, 0]},
                "c_d": {"grid": [-np.pi, 0.0, np.pi],
                        "values": [0.5, 0.5, 0.5]},
                "c_m": {"grid": [-np.pi, 0.0, np.pi], "values": [0, 0, 0]},
            }]},
            {"name": "tip", "relative_thickness": 0.18, "polars": [{
                "c_l": {"grid": [-np.pi, 0.0, np.pi], "values": [0, 0.5, 0]},
                "c_d": {"grid": [-np.pi, 0.0, np.pi],
                        "values": [0.01, 0.01, 0.01]},
                "c_m": {"grid": [-np.pi, 0.0, np.pi],
                        "values": [0, -0.1, 0]},
            }]},
        ],
    }
    out = tmp_path / "turbine.yaml"
    d = convert_iea_turbine_yaml(wt, out_path=str(out), n_span=10)
    assert d["nBlades"] == 3 and d["Rhub"] == 4.0
    assert d["Zhub"] == 150.0
    np.testing.assert_allclose(d["precone"], 4.0)
    np.testing.assert_allclose(d["shaft_tilt"], 6.0)
    # blade: 8 interior stations of a 10-point grid; r = z + Rhub
    assert d["blade"]["geometry"].shape == (8, 5)
    np.testing.assert_allclose(d["blade"]["Rtip"], 120.0)
    np.testing.assert_allclose(d["blade"]["r"],
                               np.linspace(0, 116, 10)[1:-1] + 4.0)
    np.testing.assert_allclose(d["blade"]["theta"][0], 15.0 * 8 / 9)
    # polars: alpha converted to degrees, table form
    af = d["airfoils"][1]
    assert af["key"] == ["alpha", "c_l", "c_d", "c_m"]
    np.testing.assert_allclose(af["data"][:, 0], [-180.0, 0.0, 180.0])
    np.testing.assert_allclose(af["data"][1, 1], 0.5)
    # written file round-trips through yaml and build_rotor-style access
    loaded = yaml.safe_load(open(out))
    assert loaded["turbine"]["nBlades"] == 3
    assert len(loaded["turbine"]["airfoils"][0]["data"]) == 3
    # inconsistent AOA grids must raise
    bad = clean_raft_dict(wt)
    bad["airfoils"][0]["polars"][0]["c_d"]["grid"] = [-3.0, 0.0, 3.0]
    with pytest.raises(ValueError):
        convert_iea_turbine_yaml(bad)


def test_plot_responses_extended(small_model, tmp_path):
    """9-channel PSD figure (reference raft_model.py:1262-1306)."""
    from raft_tpu.plot import plot_responses_extended

    fig, axes = plot_responses_extended(small_model)
    assert len(axes) == 9
    fig.savefig(tmp_path / "psd_ext.png")
    assert (tmp_path / "psd_ext.png").stat().st_size > 1000
    import matplotlib.pyplot as plt
    plt.close("all")


def test_plot_rotor(small_model, tmp_path):
    """Blade wireframe plot (reference raft_rotor.py:1008-1122)."""
    from raft_tpu.plot import plot_rotor

    rot = small_model.fowtList[0].rotors[0]
    fig, ax = plot_rotor(rot, draw_circle=True)
    fig.savefig(tmp_path / "rotor3d.png")
    fig2, ax2 = plot_rotor(rot, plot2d=True)
    fig2.savefig(tmp_path / "rotor2d.png")
    assert (tmp_path / "rotor3d.png").stat().st_size > 1000
    # the wireframe spans roughly the rotor diameter in z
    zlo, zhi = ax.get_zlim()
    assert zhi - zlo > rot.R_rot
    import matplotlib.pyplot as plt
    plt.close("all")


def test_adjust_wisdem(small_model, tmp_path):
    """adjustWISDEM ballast-volume update (reference
    raft_model.py:1627-1672) on a synthetic WISDEM geometry dict matching
    the model's first ballasted member."""
    import yaml as _yaml

    fowt = small_model.fowtList[0]
    m = next(mm for mm in fowt.members
             if float(np.atleast_1d(mm.l_fill)[0]) > 0)
    d0 = float(np.atleast_1d(m.d)[0])
    wis = dict(components=dict(floating_platform=dict(
        joints=[dict(name="j1", location=[0.0, 0.0,
                                          float(np.asarray(m.rA0)[2])])],
        members=[dict(name="col", joint1="j1", joint2="j2",
                      outer_shape=dict(outer_diameter=dict(values=[d0])),
                      internal_structure=dict(ballasts=[
                          dict(volume=1.0)]))])))
    old = tmp_path / "wis_old.yaml"
    new = tmp_path / "wis_new.yaml"
    _yaml.safe_dump(wis, open(old, "w"))
    small_model.adjustWISDEM(str(old), str(new))
    out = _yaml.safe_load(open(new))
    vol = out["components"]["floating_platform"]["members"][0][
        "internal_structure"]["ballasts"][0]["volume"]
    t0 = float(np.atleast_1d(m.t)[0])
    lf = float(np.atleast_1d(m.l_fill)[0])
    assert vol == pytest.approx(np.pi * ((d0 - 2 * t0) / 2) ** 2 * lf)


def test_debug_omdao_dump(tmp_path, monkeypatch):
    """RAFT_TPU_DEBUG_OMDAO dumps weis_options/weis_inputs yaml
    (reference omdao_raft.py:362-386 DEBUG_OMDAO)."""
    import yaml as _yaml

    from test_omdao import _oc3_design
    from raft_tpu.omdao import RAFT_OMDAO_Standalone, omdao_from_design

    design = _oc3_design()
    design["settings"]["max_freq"] = 0.10   # keep the replay cheap
    options, inputs, discrete_inputs = omdao_from_design(design)
    comp = RAFT_OMDAO_Standalone(**options)
    monkeypatch.setenv("RAFT_TPU_DEBUG_OMDAO", str(tmp_path))
    comp.run(inputs, discrete_inputs)
    opts = _yaml.safe_load(open(tmp_path / "weis_options.yaml"))
    assert "modeling_options" in opts and "turbine_options" in opts
    inp = _yaml.safe_load(open(tmp_path / "weis_inputs.yaml"))
    assert len(inp) > 10
