"""Pallas solve-kernel parity and the adaptive fixed-point schedule.

The kernels in ops/pallas/gj_solve.py run here under interpret mode
(conftest forces the CPU backend) — the IDENTICAL kernel code path a TPU
would compile, which is how CI proves parity without hardware.  The
acceptance bar is <= 1e-6 max relative deviation against the jnp
Gauss-Jordan path; in f64 the two agree to ~1e-12 because they are the
same algorithm in the same op order.
"""
import numpy as np
import pytest
from numpy.testing import assert_allclose

import jax
import jax.numpy as jnp

from raft_tpu import _config
from raft_tpu.ops import linalg as L
from raft_tpu.ops.pallas.gj_solve import gj_solve, impedance_gj_solve

PARITY = 1e-6     # the acceptance tolerance; f64 actuals are ~1e-12


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def forced_pallas():
    """RAFT_TPU_PALLAS=1 for the duration of a test, then restored."""
    _config.set_pallas_mode("1")
    yield
    _config.set_pallas_mode(None)


def _rel(a, b):
    return np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-12))


# ---------------------------------------------------------------------------
# kernel parity vs the jnp Gauss-Jordan and LAPACK
# ---------------------------------------------------------------------------

def test_gj_solve_matches_jnp_gj_multi_rhs(rng):
    n, k, B = 12, 3, 300
    A = rng.standard_normal((B, n, n)) + 5.0 * np.eye(n)
    b = rng.standard_normal((B, n, k))
    x_pl = np.asarray(gj_solve(jnp.asarray(A), jnp.asarray(b)))
    x_gj = np.asarray(L.gauss_jordan_solve(jnp.asarray(A), jnp.asarray(b)))
    assert _rel(x_pl, x_gj) < PARITY
    assert _rel(x_pl, np.linalg.solve(A, b)) < PARITY


def test_gj_solve_needs_pivoting():
    A = np.array([[0.0, 2.0, 1.0],
                  [1.0, 0.0, 3.0],
                  [2.0, 1.0, 0.0]])
    b = np.array([[1.0], [2.0], [3.0]])
    x = np.asarray(gj_solve(jnp.asarray(A[None]), jnp.asarray(b[None])))[0]
    assert_allclose(x, np.linalg.solve(A, b), rtol=1e-10)


def test_gj_solve_lane_padding_paths(rng):
    """Batch sizes off the 128-lane tile exercise the identity padding;
    dead lanes must not contaminate live ones."""
    n = 8
    for B in (1, 7, 127, 129, 130):
        A = rng.standard_normal((B, n, n)) + 4.0 * np.eye(n)
        b = rng.standard_normal((B, n, 1))
        x = np.asarray(gj_solve(jnp.asarray(A), jnp.asarray(b)))
        assert _rel(x, np.linalg.solve(A, b)) < PARITY, B


def test_gj_solve_mixed_row_scales(rng):
    """The impedance blocks mix ~1e7 force rows and ~1e12 moment rows;
    the in-kernel equilibration + refinement must hold parity there."""
    n, B = 12, 200
    A = (0.1 * rng.standard_normal((B, n, n)) + np.eye(n)) \
        * 10.0 ** rng.uniform(3, 10, (B, n, 1))
    b = rng.standard_normal((B, n, 1)) * 1e6
    x = np.asarray(gj_solve(jnp.asarray(A), jnp.asarray(b)))
    assert _rel(x, np.linalg.solve(A, b)) < PARITY


def test_fused_impedance_matches_assembled_reference(rng):
    """The fused kernel (Z assembled in the VMEM load stage) against the
    materialize-Z-then-solve_complex path, batched over cases."""
    nc, n, nw = 4, 6, 9
    w = np.linspace(0.2, 1.5, nw)
    M = rng.standard_normal((nc, n, n, nw)) + 5.0 * np.eye(n)[None, :, :, None]
    B = 0.1 * rng.standard_normal((nc, n, n, nw))
    C = rng.standard_normal((nc, n, n)) + 10.0 * np.eye(n)
    F = rng.standard_normal((nc, n, nw)) + 1j * rng.standard_normal((nc, n, nw))

    Z = (-w ** 2 * M + 1j * w * B + C[..., None]).astype(complex)
    Xref = np.moveaxis(np.asarray(L.solve_complex(
        jnp.moveaxis(jnp.asarray(Z), -1, -3),
        jnp.moveaxis(jnp.asarray(F), -1, -2))), -2, -1)
    Xfused = np.asarray(impedance_gj_solve(w, M, B, C, F))
    assert _rel(Xfused, Xref) < PARITY


def test_fused_impedance_unbatched_rank(rng):
    """Rank-polymorphism: the model path calls with no case batch —
    (n, n, nw) factors and a (n, nw) force."""
    n, nw = 6, 11
    w = np.linspace(0.1, 2.0, nw)
    M = rng.standard_normal((n, n, nw)) + 5.0 * np.eye(n)[:, :, None]
    B = 0.1 * rng.standard_normal((n, n, nw))
    C = rng.standard_normal((n, n)) + 10.0 * np.eye(n)
    F = rng.standard_normal((n, nw)) + 1j * rng.standard_normal((n, nw))
    Z = (-w ** 2 * M + 1j * w * B + C[..., None]).astype(complex)
    Xref = np.moveaxis(np.asarray(L.solve_complex(
        jnp.moveaxis(jnp.asarray(Z), -1, -3),
        jnp.moveaxis(jnp.asarray(F), -1, -2))), -2, -1)
    Xfused = np.asarray(impedance_gj_solve(w, M, B, C, F))
    assert Xfused.shape == (n, nw)
    assert _rel(Xfused, Xref) < PARITY


def test_impedance_solve_dispatch_parity(rng, forced_pallas):
    """impedance_solve under RAFT_TPU_PALLAS=1 (interpret mode on this
    CPU backend) must agree with the mode-0 jnp path to <= 1e-6, and the
    dispatch record must name the fused kernel."""
    nc, n, nw = 3, 6, 7
    w = np.linspace(0.3, 1.2, nw)
    M = rng.standard_normal((nc, n, n, nw)) + 5.0 * np.eye(n)[None, :, :, None]
    B = 0.1 * rng.standard_normal((nc, n, n, nw))
    C = rng.standard_normal((nc, n, n)) + 10.0 * np.eye(n)
    F = rng.standard_normal((nc, n, nw)) + 1j * rng.standard_normal((nc, n, nw))
    Xp = np.asarray(L.impedance_solve(w, M, B, C, F))
    assert L.last_dispatch()["backend"] == "pallas_fused"
    _config.set_pallas_mode("0")
    Xj = np.asarray(L.impedance_solve(w, M, B, C, F))
    assert L.last_dispatch()["backend"] in ("lu", "jnp_gj")
    assert _rel(Xp, Xj) < PARITY


def test_solve_complex_forced_pallas_vec_and_matrix(rng, forced_pallas):
    """The vec/matrix rank split of solve_complex through the Pallas
    backend: a (..., n) vector RHS and its (..., n, 1) matrix twin must
    produce the same solution."""
    n, B = 6, 40
    A = (rng.standard_normal((B, n, n)) + 1j * rng.standard_normal((B, n, n))
         + 4.0 * np.eye(n))
    b = rng.standard_normal((B, n)) + 1j * rng.standard_normal((B, n))
    xv = np.asarray(L.solve_complex(jnp.asarray(A), jnp.asarray(b)))
    assert L.last_dispatch()["backend"] == "pallas_gj"
    xm = np.asarray(L.solve_complex(jnp.asarray(A), jnp.asarray(b[..., None])))
    assert xv.shape == (B, n) and xm.shape == (B, n, 1)
    assert_allclose(xv, xm[..., 0], rtol=0, atol=0)   # identical path
    assert _rel(np.einsum("bij,bj->bi", A, xv), b) < 1e-8


def test_inv_complex_forced_pallas(rng, forced_pallas):
    """inv_complex is the k=n multi-RHS path (the model's factor-once
    Zinv); residual against the identity through the Pallas kernel."""
    n, B = 6, 20
    A = (rng.standard_normal((B, n, n)) + 1j * rng.standard_normal((B, n, n))
         + 4.0 * np.eye(n))
    Ainv = np.asarray(L.inv_complex(jnp.asarray(A)))
    eye = np.broadcast_to(np.eye(n), (B, n, n))
    assert np.max(np.abs(np.einsum("bij,bjk->bik", A, Ainv) - eye)) < 1e-8


def test_gj_solve_under_jit(rng):
    n, B = 12, 150
    A = rng.standard_normal((B, n, n)) + 5.0 * np.eye(n)
    b = rng.standard_normal((B, n, 2))
    x = np.asarray(jax.jit(gj_solve)(jnp.asarray(A), jnp.asarray(b)))
    assert _rel(x, np.linalg.solve(A, b)) < PARITY


# ---------------------------------------------------------------------------
# backend dispatch table
# ---------------------------------------------------------------------------

def test_dispatch_table(monkeypatch):
    """The (backend, n, batch) -> kernel table behind solve_complex."""
    # the CI parity job exports RAFT_TPU_PALLAS=1 — this test probes the
    # auto table, so neutralize the env first
    monkeypatch.delenv("RAFT_TPU_PALLAS", raising=False)
    # CPU backend: auto never picks an accelerator kernel
    assert L._use_gauss_jordan(12, 100000) is False
    assert L._use_pallas(12, 100000) is False
    # accelerator backend
    monkeypatch.setattr(L.jax, "default_backend", lambda: "tpu")
    assert L._use_gauss_jordan(12, 4096) is True
    assert L._use_pallas(12, 4096) is True
    assert L._use_gauss_jordan(12, 4095) is False      # batch floor
    assert L._use_pallas(12, 4095) is False
    assert L._use_gauss_jordan(18, 10 ** 6) is False   # size ceiling
    assert L._use_pallas(18, 10 ** 6) is False
    # explicit modes override the table on any backend
    _config.set_pallas_mode("1")
    try:
        assert L._use_pallas(18, 1) is True
        _config.set_pallas_mode("0")
        assert L._use_pallas(12, 10 ** 6) is False
    finally:
        _config.set_pallas_mode(None)


def test_pallas_mode_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_PALLAS", raising=False)
    monkeypatch.setenv("RAFT_TPU_PALLAS", "1")
    assert _config.pallas_mode() == "1"
    monkeypatch.setenv("RAFT_TPU_PALLAS", "bogus")
    assert _config.pallas_mode() == "auto"
    monkeypatch.delenv("RAFT_TPU_PALLAS")
    assert _config.pallas_mode() == "auto"


# ---------------------------------------------------------------------------
# adaptive fixed-point scheduling
# ---------------------------------------------------------------------------

def _mixed_step(rng, nb, nw, slow=0.9):
    """Per-item linear contraction toward distinct fixed points with
    mixed rates — items converge at different iterations."""
    rates = np.linspace(0.05, slow, nb)
    target = (rng.standard_normal((nb, 6, nw))
              + 1j * rng.standard_normal((nb, 6, nw)))

    def step(X):
        return (jnp.asarray(target)
                + jnp.asarray(rates)[:, None, None] * (X - jnp.asarray(target)))
    return step


def test_chunked_fixed_point_is_exact(rng):
    """Acceptance: chunked early-exit returns identical Xi / iters /
    converged to the full unroll on a mixed-convergence batch."""
    from raft_tpu.parallel.sweep import unrolled_fixed_point

    nb, nw, nIter = 8, 5, 12
    step = _mixed_step(rng, nb, nw)
    Xi0 = jnp.zeros((nb, 6, nw), complex) + 0.1
    full = unrolled_fixed_point(step, Xi0, nIter, 0.01, chunk=nIter)
    for chunk in (1, 2, 3, 5):
        got = unrolled_fixed_point(step, Xi0, nIter, 0.01, chunk=chunk)
        assert np.array_equal(np.asarray(full[1]), np.asarray(got[1])), chunk
        assert np.array_equal(np.asarray(full[2]), np.asarray(got[2]))
        assert np.array_equal(np.asarray(full[3]), np.asarray(got[3]))


def test_chunked_fixed_point_early_exit(rng):
    """A fast-converging batch must actually skip trailing chunks."""
    from raft_tpu.parallel.sweep import unrolled_fixed_point

    nb, nw, nIter = 6, 4, 10
    step = _mixed_step(rng, nb, nw, slow=0.2)   # everything converges fast
    Xi0 = jnp.zeros((nb, 6, nw), complex) + 0.1
    _, _, done, iters, chunks = unrolled_fixed_point(step, Xi0, nIter,
                                                     0.01, chunk=2)
    assert bool(np.all(np.asarray(done)))
    max_iters = int(np.asarray(iters).max())
    assert int(chunks) == -(-max_iters // 2)            # ceil(iters/2)
    assert int(chunks) < -(-nIter // 2)                 # skipped some


def test_chunked_fixed_point_under_jit(rng):
    from raft_tpu.parallel.sweep import unrolled_fixed_point

    nb, nw, nIter = 4, 3, 6
    step = _mixed_step(rng, nb, nw, slow=0.3)
    Xi0 = jnp.zeros((nb, 6, nw), complex) + 0.1

    fn = jax.jit(lambda x: unrolled_fixed_point(step, x, nIter, 0.01,
                                                chunk=2))
    ref = unrolled_fixed_point(step, Xi0, nIter, 0.01, chunk=2)
    got = fn(Xi0)
    assert_allclose(np.asarray(got[1]), np.asarray(ref[1]), rtol=1e-12)
    assert np.array_equal(np.asarray(got[3]), np.asarray(ref[3]))
