"""Pod-scale partition layer (parallel/partition.py).

conftest.py forces an 8-virtual-device CPU platform, so these tests
exercise real 2-D ``jax.sharding.Mesh`` topologies — ``(cases, freq)``
and ``(variants, cases)`` — without TPU hardware:

* rule matching over the REAL per-case model-state pytree (every leaf
  gets a spec; an unmatched leaf raises),
* shard/gather round-trip identity,
* 2-D vs 1-D vs unsharded sweep parity,
* padded-batch parity with a prime-sized batch (masked lanes stripped
  from results AND metrics),
* mesh-topology cache-key distinctness and the per-topology warm
  exec-cache hit,
* the bitwise-parity contract of the sharded model-level dynamics core.

Parity bars: integer solver decisions (fixed-point ``iters``,
``converged``) must be EXACT; float outputs are allowed XLA's
partition-induced reassociation jitter only (~1 ulp, bounded here at
1e-12 absolute — orders of magnitude below the 1e-6 physics ledger
tolerance).
"""
import json
import os
import sys

import numpy as np
import pytest
from numpy.testing import assert_allclose

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import errors, obs
from raft_tpu.io.designs import load_design
from raft_tpu.models.fowt import build_fowt
from raft_tpu.parallel import exec_cache, partition
from raft_tpu.parallel.sweep import make_case_solver, sweep_cases
from raft_tpu.parallel.variants import sweep_variants

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

ATOL = 1e-12    # reassociation-only float parity bar (see module doc)


@pytest.fixture(scope="module")
def fowt():
    design = load_design("Vertical_cylinder")
    # 10 coarse bins: cheap compiles AND not divisible by the 4-way
    # freq axis below, so the uneven-frequency-sharding path is the one
    # under test
    w = np.arange(0.05, 0.55, 0.05) * 2 * np.pi
    return build_fowt(design, w, depth=float(design["site"]["water_depth"]))


@pytest.fixture(scope="module")
def cases():
    rng = np.random.default_rng(11)
    n = 8
    return (4.0 + 2.0 * rng.random(n), 8.0 + 6.0 * rng.random(n),
            np.deg2rad(rng.integers(0, 360, n).astype(float)))


@pytest.fixture(scope="module")
def plain(fowt, cases):
    """Unsharded baseline batch (computed once per module)."""
    Hs, Tp, beta = cases
    return sweep_cases(fowt, Hs, Tp, beta, mesh=None, nIter=4)


def _assert_sweep_parity(sharded, plain):
    assert_allclose(np.asarray(sharded["std"]), np.asarray(plain["std"]),
                    rtol=0, atol=ATOL)
    assert_allclose(np.asarray(sharded["Xi"]), np.asarray(plain["Xi"]),
                    rtol=0, atol=ATOL)
    # solver DECISIONS must be bit-identical — resharding must never
    # change a convergence trip
    np.testing.assert_array_equal(np.asarray(sharded["iters"]),
                                  np.asarray(plain["iters"]))
    np.testing.assert_array_equal(np.asarray(sharded["converged"]),
                                  np.asarray(plain["converged"]))


# ---------------------------------------------------------------------------
# rule matching over the real model pytree
# ---------------------------------------------------------------------------

def test_rules_cover_the_real_case_state_pytree(fowt, cases):
    """Every leaf of the batched statics->dynamics state gets a spec,
    and the frequency-carrying stacks get the freq axis."""
    Hs, Tp, beta = cases
    solver = make_case_solver(fowt, nIter=2)
    st = jax.vmap(solver.setup)(jnp.asarray(Hs), jnp.asarray(Tp),
                                jnp.asarray(beta))
    specs = partition.match_partition_rules(partition.STATE_RULES, st)
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda s: isinstance(s, P))
    assert len(spec_leaves) == len(jax.tree.leaves(st))
    assert all(isinstance(s, P) for s in spec_leaves)
    # the big frequency-axis stacks are deliberately freq-sharded
    freq_specced = [name for (name, spec) in
                    zip([partition.path_name(p) for p, _ in
                         jax.tree_util.tree_flatten_with_path(st)[0]],
                        spec_leaves) if partition.FREQ in tuple(spec)]
    for expected in ("M_lin", "B_BEM", "F_lin", "u0", "drag_pre/s_q",
                     "drag_pre/u_P"):
        assert any(expected in n for n in freq_specced), expected


def test_unmatched_leaf_raises():
    with pytest.raises(errors.PartitionRuleError) as exc:
        partition.match_partition_rules(partition.CASE_INPUT_RULES,
                                        {"rogue": jnp.ones((4, 3))})
    assert "rogue" in str(exc.value)


def test_scalars_are_never_partitioned():
    specs = partition.match_partition_rules(
        (), {"a": jnp.float64(1.0), "b": jnp.ones((1, 1))})
    assert specs["a"] == P() and specs["b"] == P()


def test_resolve_spec_across_topologies():
    tpl = P(partition.BATCH, None, partition.FREQ)
    m_cf = partition.make_mesh((2, 4), ("cases", "freq"))
    assert partition.resolve_spec(tpl, m_cf) == P("cases", None, "freq")
    m_vc = partition.make_mesh((4, 2), ("variants", "cases"))
    assert partition.resolve_spec(tpl, m_vc) == P(("variants", "cases"))
    m_f = partition.make_mesh((8,), ("freq",))
    assert partition.resolve_spec(tpl, m_f) == P(None, None, "freq")
    assert partition.batch_size(m_cf) == 2
    assert partition.batch_size(m_vc) == 8
    assert partition.batch_size(None) == 1


# ---------------------------------------------------------------------------
# shard / gather round trip
# ---------------------------------------------------------------------------

def test_shard_and_gather_fns_round_trip():
    mesh = partition.make_mesh((2, 4), ("cases", "freq"))
    tree = {"M_lin": jnp.arange(8 * 6 * 6 * 12, dtype=float).reshape(
                8, 6, 6, 12),
            "C_lin": jnp.ones((8, 6, 6)),
            "F_lin": jnp.zeros((8, 6, 12)) + 1j}
    specs = partition.match_partition_rules(partition.STATE_RULES, tree)
    shard_fns, gather_fns = partition.make_shard_and_gather_fns(mesh, specs)
    placed = jax.tree.map(lambda f, x: f(x), shard_fns, tree)
    # deliberate placement: the full mesh for the freq-sharded stack
    assert len(placed["M_lin"].sharding.device_set) == 8
    assert placed["M_lin"].sharding.spec == P("cases", None, None, "freq")
    assert placed["C_lin"].sharding.spec == P("cases")
    gathered = jax.tree.map(lambda f, x: f(x), gather_fns, placed)
    for k in tree:
        assert gathered[k].sharding.spec == P()       # fully replicated
        np.testing.assert_array_equal(np.asarray(gathered[k]),
                                      np.asarray(tree[k]))


def test_pad_and_unpad_batch():
    tree = {"a": jnp.arange(13.0), "b": jnp.ones((13, 3))}
    padded, npad = partition.pad_batch(tree, 13, 8)
    assert npad == 3
    assert padded["a"].shape == (16,) and padded["b"].shape == (16, 3)
    # masked lanes repeat the last valid row (numerically benign)
    np.testing.assert_array_equal(np.asarray(padded["a"][13:]),
                                  np.full(3, 12.0))
    restored = partition.unpad_batch(padded, 13)
    np.testing.assert_array_equal(np.asarray(restored["a"]),
                                  np.arange(13.0))
    same, npad0 = partition.pad_batch(tree, 13, 1)
    assert npad0 == 0 and same is tree


# ---------------------------------------------------------------------------
# sweep parity: 2-D vs 1-D vs unsharded on 8 virtual devices
# ---------------------------------------------------------------------------

def test_sweep_2d_cases_freq_matches_unsharded(fowt, cases, plain):
    Hs, Tp, beta = cases
    mesh = partition.make_mesh((2, 4), ("cases", "freq"))
    out = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=4)
    _assert_sweep_parity(out, plain)
    assert len(out["std"].sharding.device_set) == 8


def test_sweep_2d_variants_cases_mesh_runs_case_batch(fowt, cases, plain):
    """A (variants, cases) mesh runs a cases-only sweep over ALL its
    devices: the batch axis shards over the product of both axes."""
    Hs, Tp, beta = cases
    mesh = partition.make_mesh((4, 2), ("variants", "cases"))
    out = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=4)
    _assert_sweep_parity(out, plain)
    assert len(out["std"].sharding.device_set) == 8


def test_padded_prime_batch_parity_and_manifest(fowt, tmp_path,
                                                monkeypatch):
    """A prime-sized batch on a 2-D mesh: padded lanes must be invisible
    in results, metrics, the manifest and the trend store."""
    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path))
    obs.reset_all()
    rng = np.random.default_rng(5)
    n = 13
    Hs = 4.0 + 2.0 * rng.random(n)
    Tp = 8.0 + 6.0 * rng.random(n)
    beta = np.zeros(n)
    plain13 = sweep_cases(fowt, Hs, Tp, beta, mesh=None, nIter=3)
    mesh = partition.make_mesh((2, 4), ("cases", "freq"))
    out = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=3)
    assert np.asarray(out["std"]).shape == (13, 6)
    assert np.asarray(out["Xi"]).shape[0] == 13
    assert np.asarray(out["iters"]).shape == (13,)
    _assert_sweep_parity(out, plain13)
    # metrics saw the TRUE batch size, not the padded one
    snap = obs.snapshot()
    batch = snap["raft_sweep_batch_cases"]["series"]
    assert {s["value"] for s in batch} == {13.0}
    meshg = snap["raft_tpu_mesh_devices"]["series"]
    assert meshg[0]["labels"]["topology"] == "cases=2xfreq=4"
    # the manifest records the full topology + the pad count
    manifests = sorted(f for f in os.listdir(tmp_path)
                       if f.endswith(".manifest.json"))
    docs = [json.load(open(os.path.join(tmp_path, f))) for f in manifests]
    doc = [d for d in docs if d["config"].get("mesh")][-1]
    assert doc["config"]["mesh"]["axes"] == ["cases", "freq"]
    assert doc["config"]["mesh"]["shape"] == [2, 4]
    # padding goes to the BATCH-shard multiple (the cases axis is 2-way
    # on this mesh; freq does not consume batch lanes): 13 -> 14
    assert doc["extra"]["partition"]["npad"] == 1
    assert doc["extra"]["partition"]["rules"]
    # ... and the trend store + obsctl trend expose the topology column
    from raft_tpu.obs import trendstore
    facts = trendstore.facts_from_manifest(doc)
    assert facts["mesh"] == "cases=2xfreq=4"
    assert facts["mesh_devices"] == 8
    from tools import obsctl
    rows = obsctl._store_trend_rows(os.path.join(str(tmp_path),
                                                 "trend.sqlite"))
    assert any(r.get("mesh") == "cases=2xfreq=4" for r in rows)


def test_variants_2d_mesh_parity(fowt):
    nmem = len(fowt.members)
    nv = 5                       # pads to 8 on the 2-D mesh
    scales = np.linspace(0.9, 1.1, nv)
    thetas = {"d_scale": np.ones((nv, nmem, 2)) * scales[:, None, None]}
    kw = dict(ballast=False, nIter=3, newton_iters=4)
    plain = sweep_variants(fowt, thetas, mesh=None, **kw)
    mesh = partition.make_mesh((4, 2), ("variants", "cases"))
    out = sweep_variants(fowt, thetas, mesh=mesh, **kw)
    for k in ("std", "Xi", "mass", "Xeq", "GMT"):
        assert np.asarray(out[k]).shape == np.asarray(plain[k]).shape
        assert_allclose(np.asarray(out[k]), np.asarray(plain[k]),
                        rtol=1e-12, atol=ATOL)


# ---------------------------------------------------------------------------
# model-level dynamics core: bitwise through the freq axis
# ---------------------------------------------------------------------------

def test_sharded_dynamics_core_is_bitwise(rng):
    from raft_tpu.model import _dyn_solve_core, _dyn_solve_jit

    nw, n6, nH = 10, 6, 3
    Z = rng.random((nw, n6, n6)) + 1j * rng.random((nw, n6, n6))
    Zinv = np.linalg.inv(Z)
    F = rng.random((nH, n6, nw)) + 1j * rng.random((nH, n6, nw))
    Xi0, rel0 = jax.jit(_dyn_solve_core)(Zinv, Z, F)
    for shape, axes in (((8,), ("freq",)), ((2, 4), ("cases", "freq"))):
        mesh = partition.make_mesh(shape, axes)
        Xi1, rel1 = _dyn_solve_jit(mesh)(Zinv, Z, F)
        # element-wise solve: sharding must not move a single bit
        np.testing.assert_array_equal(np.asarray(Xi0), np.asarray(Xi1))
        # the telemetry residual reduces over the sharded axis —
        # reassociation jitter only
        assert_allclose(np.asarray(rel1), np.asarray(rel0),
                        rtol=0, atol=1e-14)
    # distinct topologies get distinct compiled programs
    assert _dyn_solve_jit(partition.make_mesh((8,), ("freq",))) is not \
        _dyn_solve_jit(partition.make_mesh((2, 4), ("cases", "freq")))


# ---------------------------------------------------------------------------
# executable-cache topology identity
# ---------------------------------------------------------------------------

def test_cache_key_distinguishes_mesh_topologies():
    m_cf = partition.make_mesh((2, 4), ("cases", "freq"))
    m_vc = partition.make_mesh((2, 4), ("variants", "cases"))
    m_fc = partition.make_mesh((4, 2), ("cases", "freq"))
    keys = {exec_cache.make_key(fn="sweep_cases", model="sha256:aa",
                                mesh=partition.mesh_facts(m), rules="r1")
            for m in (m_cf, m_vc, m_fc)}
    # same sorted shape, SAME device count — but three distinct programs
    assert len(keys) == 3
    # the rule fingerprint is part of the identity too
    assert exec_cache.make_key(
        fn="s", mesh=partition.mesh_facts(m_cf),
        rules=partition.rules_fingerprint(partition.STATE_RULES)) != \
        exec_cache.make_key(
            fn="s", mesh=partition.mesh_facts(m_cf),
            rules=partition.rules_fingerprint(partition.CASE_INPUT_RULES))


def test_warm_cache_hit_per_topology(fowt, cases, tmp_path, monkeypatch):
    """Each distinct mesh topology warms its own cache entry: a rerun on
    the same topology skips lower+compile, a different topology on the
    same devices is a miss."""
    Hs, Tp, beta = cases
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path))
    exec_cache.reset_stats()
    mesh = partition.make_mesh((2, 4), ("cases", "freq"))
    out1 = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=3)
    agg = obs.aggregate()
    assert agg["sweep_lower"][1] == 1 and agg["sweep_compile"][1] == 1
    assert exec_cache.stats()["misses"] == 1

    obs.reset_all()
    out2 = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=3)
    agg = obs.aggregate()
    assert "sweep_lower" not in agg and "sweep_compile" not in agg
    assert exec_cache.stats()["hits"] == 1
    np.testing.assert_array_equal(np.asarray(out1["Xi"]),
                                  np.asarray(out2["Xi"]))

    # same devices, same sorted shape — different topology: a MISS
    obs.reset_all()
    other = partition.make_mesh((4, 2), ("variants", "cases"))
    sweep_cases(fowt, Hs, Tp, beta, mesh=other, nIter=3)
    agg = obs.aggregate()
    assert agg["sweep_lower"][1] == 1
    assert exec_cache.stats()["misses"] == 2


# ---------------------------------------------------------------------------
# mesh construction / ambient topology / multi-process plumbing
# ---------------------------------------------------------------------------

def test_make_mesh_and_facts():
    mesh = partition.make_mesh((2, 4), ("cases", "freq"))
    facts = partition.mesh_facts(mesh)
    assert facts["axes"] == ["cases", "freq"]
    assert facts["shape"] == [2, 4]
    assert facts["devices"] == 8
    assert facts["topology"] == "cases=2xfreq=4"
    assert facts["processes"] == 1
    assert partition.mesh_facts(None) is None
    assert partition.mesh_key(mesh) == (("cases", 2), ("freq", 4))
    with pytest.raises(errors.PartitionRuleError):
        partition.make_mesh((4, 4), ("cases", "freq"))   # 16 > 8 devices
    with pytest.raises(errors.PartitionRuleError):
        partition.make_mesh((2, 4), ("cases",))          # shape/axes clash


def test_ambient_mesh_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_MESH", raising=False)
    assert partition.ambient_mesh() is None
    monkeypatch.setenv("RAFT_TPU_MESH", "cases=2,freq=4")
    mesh = partition.ambient_mesh()
    assert tuple(mesh.axis_names) == ("cases", "freq")
    assert partition.mesh_facts(mesh)["topology"] == "cases=2xfreq=4"
    monkeypatch.setenv("RAFT_TPU_MESH", "freq=8")
    assert partition.mesh_facts(
        partition.ambient_mesh())["topology"] == "freq=8"


def test_ensure_distributed_single_process_is_a_noop(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_DIST", raising=False)
    monkeypatch.delenv("RAFT_TPU_COORDINATOR", raising=False)
    facts = partition.ensure_distributed()
    assert facts == {"process_index": 0, "process_count": 1}


def test_rules_fingerprint_stability():
    f1 = partition.rules_fingerprint(partition.STATE_RULES)
    assert f1 == partition.rules_fingerprint(partition.STATE_RULES)
    assert f1 != partition.rules_fingerprint(partition.CASE_INPUT_RULES)
    # editing a rule changes the fingerprint (cache invalidation)
    edited = partition.STATE_RULES[:-1] + ((r".*", P(None)),)
    assert f1 != partition.rules_fingerprint(edited)
