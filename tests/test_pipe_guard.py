"""``obsctl`` / ``raftlint`` piped into ``head`` must exit 0.

Under ``set -o pipefail`` (the CI shell), an unguarded BrokenPipeError
— raised when the downstream reader closes early — turns a routine
``obsctl tail ... | head`` into a red job and a Python traceback on
stderr.  Both CLIs guard the write AND the interpreter-shutdown flush
of sys.stdout.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _piped(cmd: str):
    return subprocess.run(
        ["bash", "-c", f"set -o pipefail; {cmd}"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_obsctl_tail_into_head(tmp_path):
    # enough rendered lines to overflow the 64 KiB pipe buffer after
    # head exits, forcing the EPIPE on a mid-stream write
    events = tmp_path / "sweep_pipe.events.jsonl"
    with open(events, "w") as f:
        for i in range(20000):
            f.write(json.dumps({"type": "span_open", "t": 1.0 + i,
                                "name": f"span_{i}"}) + "\n")
    p = _piped(f"{sys.executable} tools/obsctl.py tail --spans "
               f"{events} | head -2")
    assert p.returncode == 0, p.stderr
    assert "Traceback" not in p.stderr
    assert len(p.stdout.splitlines()) == 2


def test_raftlint_json_into_head():
    p = _piped(f"{sys.executable} -m tools.raftlint --format json "
               f"| head -c 64")
    assert p.returncode == 0, p.stderr
    assert "Traceback" not in p.stderr
    assert p.stdout        # head got the start of the report
