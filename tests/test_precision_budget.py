"""f32-vs-f64 error budget for the TPU throughput mode.

BASELINE.md's accuracy target is RAOs matching the CPU reference to 1e-6;
the benchmark (`bench.py`) runs the sweep in f32 on the TPU
(RAFT_TPU_X64=0), while the regression tests all run x64.  This test
quantifies what that precision switch costs on the flagship workload —
the full VolturnUS-S case solve (drag-linearization fixed point around
the batched complex 6x6 solve, 100 bins, nIter=10) — by running the
identical pipeline in both modes in fresh subprocesses (the x64 flag is
process-global) and comparing the 6-DOF response standard deviations.

Measured budget on this host (CPU backend, 2026-07): max relative
deviation 8.6e-7 across all DOFs — the f32 mode stays inside the 1e-6
RAO target for single-case solves.  Asserted at 5e-6 to allow for
backend-to-backend rounding differences (TPU matmul reassociation).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
import numpy as np
import raft_tpu
from raft_tpu.models.fowt import build_fowt
from raft_tpu.parallel.sweep import make_case_solver
from raft_tpu.io.designs import load_design

design = load_design('VolturnUS-S')
s = design.get('settings', {})
df = s.get('min_freq', 0.01)
w = np.arange(df, s.get('max_freq', 1.0) + 0.5 * df, df) * 2 * np.pi
fowt = build_fowt(design, w, depth=float(design['site']['water_depth']))
solver = make_case_solver(fowt, nIter=10)
out = solver(np.float64(6.0), np.float64(12.0), np.deg2rad(30.0))
np.save(sys.argv[1], np.asarray(out['std'], np.float64))
"""


def _run(x64_flag, out_path):
    env = dict(os.environ, RAFT_TPU_X64=x64_flag, JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run([sys.executable, "-c", CODE, out_path], env=env,
                          capture_output=True, text=True, timeout=600,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return np.load(out_path)


def test_f32_response_std_budget(tmp_path):
    std64 = _run("1", str(tmp_path / "std64.npy"))
    std32 = _run("0", str(tmp_path / "std32.npy"))
    assert np.all(np.isfinite(std64)) and np.all(np.isfinite(std32))
    rel = np.abs(std64 - std32) / np.maximum(np.abs(std64), 1e-12)
    assert rel.max() < 5e-6, f"f32 deviation {rel} exceeds budget"


AERO_CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
import numpy as np
import jax.numpy as jnp
import raft_tpu
from raft_tpu.models.fowt import build_fowt
from raft_tpu.models.rotor import calc_aero
from raft_tpu.io.designs import load_design

design = load_design('VolturnUS-S')
w = np.arange(1, 101) * 0.004 * 2 * np.pi
fowt = build_fowt(design, w, depth=float(design['site']['water_depth']))
case = dict(zip(design['cases']['keys'], design['cases']['data'][0]))
out = calc_aero(fowt.rotors[0], w, case, r6=jnp.zeros(6))
np.savez(sys.argv[1],
         f0=np.asarray(out['f0'], np.float64),
         b00=np.asarray(out['b'][0, 0], np.float64),
         dT_dU=np.float64(out['derivs']['dT_dU']))
"""


def test_f32_calc_aero_guard(tmp_path):
    """The BEM induction bracket test needs ~1e-12 cancellation resolution;
    without the rotor.f64_host guard the f32 bisection falls into the
    propeller-brake bracket and thrust collapses ~400x (the root cause of
    BENCH_r03's 35%-median on-TPU deviation).  The guard must keep f32-mode
    calc_aero at f32-cast-of-f64 accuracy."""
    outs = {}
    for flag in ("1", "0"):
        path = str(tmp_path / f"aero{flag}.npz")
        env = dict(os.environ, RAFT_TPU_X64=flag, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        proc = subprocess.run([sys.executable, "-c", AERO_CODE, path],
                              env=env, capture_output=True, text=True,
                              timeout=600, cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs[flag] = dict(np.load(path))
    for key in ("f0", "b00", "dT_dU"):
        a, b = outs["1"][key], outs["0"][key]
        rel = np.abs(a - b) / np.maximum(np.abs(a).max(), 1e-12)
        assert rel.max() < 1e-5, f"{key}: f32-mode aero deviates {rel.max()}"


VARIANT_CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
import numpy as np
import raft_tpu
import bench
from raft_tpu.parallel.variants import make_variant_solver

design = bench._design()
base = bench._base_fowt(design)
thetas = bench._thetas(design, base, 6)
F_env, A_turb, B_turb = bench._aero_constants(design, base)
solver = make_variant_solver(base, Hs=6.0, Tp=12.0, ballast=True,
                             F_env=F_env, A_turb=A_turb, B_turb=B_turb,
                             nIter=10, tol=-1.0, newton_iters=10)
out = jax.jit(solver.batched)(thetas)
np.save(sys.argv[1], np.asarray(out['std'], np.float64))
"""


@pytest.mark.slow
def test_f32_variant_pipeline_budget(tmp_path):
    """The budget on the workload the bench's accuracy gate measures: the
    full variant pipeline (traced geometry + ballast trim + Newton statics
    + drag fixed point + RAO solve) with aero constants.  This is the
    pipeline whose f32 run sat at a median 35% deviation in round 3 (bad
    f32 aero constants); with the f64_host guard the measured CPU budget
    is ~4e-6 median / ~5e-5 max on the 16-variant gate batch."""
    env_common = dict(os.environ, JAX_PLATFORMS="cpu",
                      PALLAS_AXON_POOL_IPS="", RAFT_BENCH_NW="200")
    outs = {}
    for flag in ("1", "0"):
        path = str(tmp_path / f"var{flag}.npy")
        env = dict(env_common, RAFT_TPU_X64=flag)
        proc = subprocess.run([sys.executable, "-c", VARIANT_CODE, path],
                              env=env, capture_output=True, text=True,
                              timeout=1800, cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs[flag] = np.load(path)
    std64, std32 = outs["1"], outs["0"]
    assert np.all(np.isfinite(std64)) and np.all(np.isfinite(std32))
    dev = np.abs(std32 - std64) / np.maximum(np.abs(std64), 1e-12)
    # same channel masking doctrine as bench._accuracy_gate
    mask = np.zeros_like(dev, dtype=bool)
    for grp in (slice(0, 3), slice(3, 6)):
        gscale = np.abs(std64[:, grp]).max()
        for j in range(grp.start, grp.stop):
            peak = np.abs(std64[:, j]).max()
            if peak > 1e-4 * gscale:
                mask[:, j] = np.abs(std64[:, j]) > 1e-3 * peak
    assert mask.any()
    assert np.median(dev[mask]) < 1e-4, dev
    assert dev[:, 0].max() < 1e-3, dev
