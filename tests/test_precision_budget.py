"""f32-vs-f64 error budget for the TPU throughput mode.

BASELINE.md's accuracy target is RAOs matching the CPU reference to 1e-6;
the benchmark (`bench.py`) runs the sweep in f32 on the TPU
(RAFT_TPU_X64=0), while the regression tests all run x64.  This test
quantifies what that precision switch costs on the flagship workload —
the full VolturnUS-S case solve (drag-linearization fixed point around
the batched complex 6x6 solve, 100 bins, nIter=10) — by running the
identical pipeline in both modes in fresh subprocesses (the x64 flag is
process-global) and comparing the 6-DOF response standard deviations.

Measured budget on this host (CPU backend, 2026-07): max relative
deviation 8.6e-7 across all DOFs — the f32 mode stays inside the 1e-6
RAO target for single-case solves.  Asserted at 5e-6 to allow for
backend-to-backend rounding differences (TPU matmul reassociation).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
import numpy as np
import raft_tpu
from raft_tpu.models.fowt import build_fowt
from raft_tpu.parallel.sweep import make_case_solver
from raft_tpu.io.designs import load_design

design = load_design('VolturnUS-S')
s = design.get('settings', {})
df = s.get('min_freq', 0.01)
w = np.arange(df, s.get('max_freq', 1.0) + 0.5 * df, df) * 2 * np.pi
fowt = build_fowt(design, w, depth=float(design['site']['water_depth']))
solver = make_case_solver(fowt, nIter=10)
out = solver(np.float64(6.0), np.float64(12.0), np.deg2rad(30.0))
np.save(sys.argv[1], np.asarray(out['std'], np.float64))
"""


def _run(x64_flag, out_path):
    env = dict(os.environ, RAFT_TPU_X64=x64_flag, JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run([sys.executable, "-c", CODE, out_path], env=env,
                          capture_output=True, text=True, timeout=600,
                          cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-2000:]
    return np.load(out_path)


def test_f32_response_std_budget(tmp_path):
    std64 = _run("1", str(tmp_path / "std64.npy"))
    std32 = _run("0", str(tmp_path / "std32.npy"))
    assert np.all(np.isfinite(std64)) and np.all(np.isfinite(std32))
    rel = np.abs(std64 - std32) / np.maximum(np.abs(std64), 1e-12)
    assert rel.max() < 5e-6, f"f32 deviation {rel} exceeds budget"
