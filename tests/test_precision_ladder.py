"""Mixed-precision solve ladder: config knobs, in-kernel numerics,
per-lane promotion, dispatch-fact recording, and exec-cache identity.

The ladder contract (docs/performance.md "Layer 6"): under
``RAFT_TPU_PRECISION=mixed`` the factorization runs at a low width
(f32 default, bf16 opt-in) while the refinement residual and correction
accumulate at the full input width inside the kernel; lanes whose final
relative residual exceeds the promotion tolerance are re-solved at the
full width in a second pass.  Accuracy is therefore f64-level no matter
how the low rung behaves — the promotion mask, not hope, carries the
guarantee — and every solve records which rung it ran on
(``linalg.last_dispatch()``) so manifests and the exec-cache key can
tell a mixed program from an f64 one.
"""
import numpy as np
import pytest
from numpy.testing import assert_allclose

import jax
import jax.numpy as jnp

from raft_tpu import _config
from raft_tpu.ops import linalg as L
from raft_tpu.ops import precision as prec
from raft_tpu.ops.pallas.gj_solve import gj_solve, impedance_gj_solve


@pytest.fixture
def rng():
    return np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _clear_overrides():
    """Precision/pallas overrides are process-global; never leak them."""
    yield
    _config.set_precision_mode(None)
    _config.set_precision_width(None)
    _config.set_pallas_mode(None)


def _rel(a, b):
    return np.max(np.abs(a - b) / np.maximum(np.abs(b), 1e-12))


def _dev(a, b):
    """Max deviation normalized by the reference's own peak — the
    ledger-style measure, immune to near-zero elements."""
    return np.max(np.abs(np.asarray(a) - np.asarray(b))) \
        / np.max(np.abs(np.asarray(b)))


def _ill_conditioned(rng, A, lanes, cond=1e9):
    """Rewrite the first ``lanes`` systems to a prescribed condition
    number via their SVD — the f32 rung cannot refine these below the
    default tolerance, so they MUST promote."""
    n = A.shape[-1]
    for i in range(lanes):
        U, _, Vt = np.linalg.svd(A[i])
        A[i] = (U * np.geomspace(1.0, 1.0 / cond, n)) @ Vt
    return A


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------

def test_precision_mode_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_PRECISION", raising=False)
    assert _config.precision_mode() == "f64"          # default
    monkeypatch.setenv("RAFT_TPU_PRECISION", "mixed")
    assert _config.precision_mode() == "mixed"
    monkeypatch.setenv("RAFT_TPU_PRECISION", "bogus")
    assert _config.precision_mode() == "f64"          # unknown -> default
    _config.set_precision_mode("f32")                 # override beats env
    assert _config.precision_mode() == "f32"
    with pytest.raises(ValueError):
        _config.set_precision_mode("f16")


def test_precision_width_and_tol_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_PRECISION_WIDTH", raising=False)
    monkeypatch.delenv("RAFT_TPU_PRECISION_TOL", raising=False)
    assert _config.precision_width() == "f32"
    monkeypatch.setenv("RAFT_TPU_PRECISION_WIDTH", "bf16")
    assert _config.precision_width() == "bf16"
    monkeypatch.setenv("RAFT_TPU_PRECISION_WIDTH", "f8")
    assert _config.precision_width() == "f32"         # unknown -> f32
    with pytest.raises(ValueError):
        _config.set_precision_width("f8")
    assert _config.precision_tol() == 1e-9            # default
    monkeypatch.setenv("RAFT_TPU_PRECISION_TOL", "1e-6")
    assert _config.precision_tol() == 1e-6
    monkeypatch.setenv("RAFT_TPU_PRECISION_TOL", "not-a-number")
    assert _config.precision_tol() == 1e-9


def test_shared_precision_helpers():
    """One underflow-floor source for both GJ implementations
    (dedupe satellite): dtype-aware, bf16 shares f32's exponent."""
    assert prec.equilibration_eps(jnp.float64) == 1e-300
    assert prec.equilibration_eps(jnp.float32) == 1e-30
    assert prec.equilibration_eps(jnp.bfloat16) == 1e-30
    assert prec.factor_dtype("f32") == jnp.float32
    assert prec.factor_dtype("bf16") == jnp.bfloat16
    assert prec.factor_dtype("nonsense") == jnp.float32
    assert prec.narrows(jnp.float32, jnp.float64)
    assert not prec.narrows(jnp.float32, jnp.float32)
    assert prec.narrows(jnp.bfloat16, jnp.float32)
    assert prec.width_name(jnp.float64) == "f64"
    assert prec.width_name(jnp.float32) == "f32"
    assert prec.width_name(jnp.bfloat16) == "bf16"


# ---------------------------------------------------------------------------
# in-kernel ladder numerics (interpret mode on this CPU backend)
# ---------------------------------------------------------------------------

def test_mixed_kernel_reaches_f64_accuracy(rng):
    """f32 factorization + in-kernel f64 refinement must land at
    f64-level accuracy on well-conditioned systems — and beat a pure
    f32 solve by orders of magnitude."""
    n, B = 12, 256
    A = rng.standard_normal((B, n, n)) + 5.0 * np.eye(n)
    b = rng.standard_normal((B, n, 2))
    truth = np.linalg.solve(A, b)
    xm, st = gj_solve(jnp.asarray(A), jnp.asarray(b), refine=2,
                      precision="mixed", promote_tol=1e-9,
                      return_stats=True)
    err_mixed = _rel(np.asarray(xm), truth)
    x32 = gj_solve(jnp.asarray(A, jnp.float32), jnp.asarray(b, jnp.float32))
    err_f32 = _rel(np.asarray(x32, np.float64), truth)
    assert err_mixed < 1e-10
    assert err_mixed < err_f32 / 100.0
    assert int(np.asarray(st["promoted"])) == 0       # nothing promoted
    assert st["lanes"] == B
    assert float(np.asarray(st["resid_max"])) < 1e-9


def test_mixed_kernel_promotes_ill_lanes(rng):
    """Lanes the f32 rung cannot refine below tolerance are re-solved
    at f64 — the count is exact and the OUTPUT of promoted lanes
    matches the full-f64 solve, while untouched lanes keep their
    mixed-ladder values."""
    n, B, ill = 8, 64, 9
    A = rng.standard_normal((B, n, n)) + 5.0 * np.eye(n)
    A = _ill_conditioned(rng, A, ill)
    b = rng.standard_normal((B, n, 1))
    x, st = gj_solve(jnp.asarray(A), jnp.asarray(b), refine=2,
                     precision="mixed", promote_tol=1e-9,
                     return_stats=True)
    assert int(np.asarray(st["promoted"])) == ill
    assert float(np.asarray(st["resid_max"])) > 1e-9  # the signal fired
    xf64 = np.asarray(gj_solve(jnp.asarray(A), jnp.asarray(b), refine=2))
    # promoted lanes ran the identical full-width path
    assert_allclose(np.asarray(x)[:ill], xf64[:ill], rtol=1e-12, atol=0)
    # and the whole batch satisfies the original systems
    r = np.abs(np.einsum("bij,bjk->bik", A, np.asarray(x)) - b)
    assert np.max(r) / np.max(np.abs(b)) < 1e-6


def test_mixed_kernel_bf16_rung_still_accurate(rng):
    """The aggressive bf16 rung: whatever the 8-bit mantissa does to
    convergence, promotion guarantees the contract — output error stays
    ledger-grade."""
    n, B = 8, 128
    A = rng.standard_normal((B, n, n)) + 6.0 * np.eye(n)
    b = rng.standard_normal((B, n, 1))
    x, st = gj_solve(jnp.asarray(A), jnp.asarray(b), refine=2,
                     precision="mixed", factor_dtype=jnp.bfloat16,
                     promote_tol=1e-9, return_stats=True)
    assert _dev(np.asarray(x), np.linalg.solve(A, b)) < 1e-7
    assert st["lanes"] == B


def test_mixed_fused_impedance_parity(rng):
    """The fused impedance kernel's mixed ladder against its own f64
    path — same assembly, same physics, low-width elimination."""
    nc, n, nw = 4, 6, 9
    w = np.linspace(0.2, 1.5, nw)
    M = rng.standard_normal((nc, n, n, nw)) + 5.0 * np.eye(n)[None, :, :, None]
    B = 0.1 * rng.standard_normal((nc, n, n, nw))
    C = rng.standard_normal((nc, n, n)) + 10.0 * np.eye(n)
    F = rng.standard_normal((nc, n, nw)) + 1j * rng.standard_normal((nc, n, nw))
    Xref = np.asarray(impedance_gj_solve(w, M, B, C, F))
    Xm, st = impedance_gj_solve(w, M, B, C, F, refine=2, precision="mixed",
                                promote_tol=1e-9, return_stats=True)
    assert _rel(np.asarray(Xm), Xref) < 1e-10
    assert int(np.asarray(st["promoted"])) == 0
    assert st["lanes"] == nc * nw


def test_unknown_precision_raises_typed():
    from raft_tpu import errors

    A = jnp.eye(4)[None]
    b = jnp.ones((1, 4, 1))
    with pytest.raises(errors.ModelConfigError):
        gj_solve(A, b, precision="f16")
    with pytest.raises(errors.ModelConfigError):
        impedance_gj_solve(jnp.ones(1), jnp.zeros((4, 4, 1)),
                           jnp.zeros((4, 4, 1)), jnp.eye(4),
                           jnp.ones((4, 1)) + 0j, precision="f16")


def test_gj_solve_under_jit_with_stats(rng):
    """The stats are traced scalars — the mixed path must be jittable
    end to end (the dynamics hot path calls it inside jit)."""
    n, B = 8, 130                                     # off-tile padding
    A = rng.standard_normal((B, n, n)) + 5.0 * np.eye(n)
    b = rng.standard_normal((B, n, 1))

    fn = jax.jit(lambda a, r: gj_solve(a, r, refine=2, precision="mixed",
                                       promote_tol=1e-9,
                                       return_stats=True))
    x, st = fn(jnp.asarray(A), jnp.asarray(b))
    assert _rel(np.asarray(x), np.linalg.solve(A, b)) < 1e-10
    assert int(np.asarray(st["promoted"])) == 0


# ---------------------------------------------------------------------------
# dispatch recording: the RAFT_TPU_PALLAS x RAFT_TPU_PRECISION matrix
# ---------------------------------------------------------------------------

def _impedance_case(rng, nc=3, n=6, nw=7):
    w = np.linspace(0.3, 1.2, nw)
    M = rng.standard_normal((nc, n, n, nw)) + 5.0 * np.eye(n)[None, :, :, None]
    B = 0.1 * rng.standard_normal((nc, n, n, nw))
    C = rng.standard_normal((nc, n, n)) + 10.0 * np.eye(n)
    F = rng.standard_normal((nc, n, nw)) + 1j * rng.standard_normal((nc, n, nw))
    return w, M, B, C, F


@pytest.mark.parametrize("pallas", ["0", "1"])
@pytest.mark.parametrize("mode", ["f64", "mixed", "f32"])
def test_dispatch_matrix_records_precision_facts(rng, pallas, mode):
    """Every (RAFT_TPU_PALLAS, RAFT_TPU_PRECISION) combination must
    solve correctly AND record the precision facts manifests and the
    exec-cache key rely on."""
    w, M, B, C, F = _impedance_case(rng)
    Xref = np.asarray(L.impedance_solve(w, M, B, C, F))  # ambient f64
    _config.set_pallas_mode(pallas)
    _config.set_precision_mode(mode)
    X = np.asarray(L.impedance_solve(w, M, B, C, F))
    d = L.last_dispatch()
    assert d["precision"] == mode
    if pallas == "1":
        assert d["backend"] == "pallas_fused" and d["fused"]
    else:
        assert d["backend"] in ("lu", "jnp_gj")
    if mode == "mixed":
        assert d["solve_width"] == "f64"
        assert d["factor_width"] == "f32"
        assert d["promote_tol"] == _config.precision_tol()
        assert _dev(X, Xref) < 1e-9                   # under the ledger bar
    elif mode == "f32":
        assert d["solve_width"] == "f32"
        assert d["factor_width"] is None
        assert _dev(X, Xref) < 1e-4                   # the explicit rung
    else:
        assert d["solve_width"] == "f64"
        assert d["factor_width"] is None
        assert_allclose(X, Xref, rtol=1e-12)
    assert X.dtype == Xref.dtype                      # width restored


def test_mixed_degenerates_on_f32_inputs_recorded(rng):
    """A mixed request whose factor width is not strictly below the
    input width degenerates to the native solve — recorded, never
    silent."""
    n, B = 6, 20
    A = (rng.standard_normal((B, n, n)) + 4.0 * np.eye(n)
         + 1j * 0.1 * rng.standard_normal((B, n, n))).astype(np.complex64)
    b = (rng.standard_normal((B, n)) + 0j).astype(np.complex64)
    _config.set_precision_mode("mixed")
    x = np.asarray(L.solve_complex(jnp.asarray(A), jnp.asarray(b)))
    d = L.last_dispatch()
    assert d["precision"] == "mixed"
    assert d["factor_width"] is None                  # no lower rung
    assert d.get("precision_degenerate") is True
    assert x.dtype == np.complex64
    assert _dev(np.einsum("bij,bj->bi", A, x), b) < 1e-4


def test_dispatch_record_cleared_between_modes(rng):
    """A later single-width dispatch must not keep wearing an earlier
    mixed dispatch's precision facts (cleared, not merged)."""
    w, M, B, C, F = _impedance_case(rng)
    _config.set_precision_mode("mixed")
    L.impedance_solve(w, M, B, C, F)
    assert L.last_dispatch()["factor_width"] == "f32"
    _config.set_precision_mode(None)
    L.impedance_solve(w, M, B, C, F)
    d = L.last_dispatch()
    assert d["precision"] == "f64"
    assert d["factor_width"] is None
    assert "precision_degenerate" not in d


def test_mixed_ladder_on_jnp_gj_backend(rng, monkeypatch):
    """RAFT_TPU_PRECISION is honored on every RAFT_TPU_PALLAS rung —
    here the jnp Gauss-Jordan backend (batch-first _mixed_ladder)."""
    monkeypatch.setattr(L, "_use_pallas", lambda n, b: False)
    monkeypatch.setattr(L, "_use_gauss_jordan", lambda n, b: True)
    _config.set_precision_mode("mixed")
    n, B = 6, 32
    A = (rng.standard_normal((B, n, n)) + 4.0 * np.eye(n)
         + 1j * 0.1 * rng.standard_normal((B, n, n)))
    b = rng.standard_normal((B, n)) + 1j * rng.standard_normal((B, n))
    x = np.asarray(L.solve_complex(jnp.asarray(A), jnp.asarray(b)))
    assert L.last_dispatch()["backend"] == "jnp_gj"
    assert L.last_dispatch()["factor_width"] == "f32"
    assert _rel(np.einsum("bij,bj->bi", A, x), b) < 1e-10


def test_mixed_ladder_on_lu_backend_promotes(rng):
    """The LU rung's _mixed_ladder with genuinely ill-conditioned lanes:
    promotion re-solves them at the full width."""
    _config.set_pallas_mode("0")
    _config.set_precision_mode("mixed")
    n, B, ill = 8, 24, 5
    Ar = rng.standard_normal((B, n, n)) + 5.0 * np.eye(n)
    Ar = _ill_conditioned(rng, Ar, ill, cond=1e8)
    A = Ar + 0j
    b = rng.standard_normal((B, n)) + 0j
    x = np.asarray(L.solve_complex(jnp.asarray(A), jnp.asarray(b)))
    assert L.last_dispatch()["backend"] == "lu"
    xref = np.linalg.solve(A, b[..., None])[..., 0]
    assert _dev(x, xref) < 1e-6


def test_mixed_ladder_on_lu_backend_bf16_width(rng):
    """LAPACK LU has no bf16 kernel: the LU cell's bf16 low rung must
    route through the jnp Gauss-Jordan core instead of crashing at
    trace time — and promotion still guarantees the contract."""
    _config.set_pallas_mode("0")
    _config.set_precision_mode("mixed")
    _config.set_precision_width("bf16")
    n, B = 6, 16
    A = (rng.standard_normal((B, n, n)) + 6.0 * np.eye(n)) + 0j
    b = rng.standard_normal((B, n)) + 0j
    x = np.asarray(L.solve_complex(jnp.asarray(A), jnp.asarray(b)))
    d = L.last_dispatch()
    assert d["backend"] == "lu"
    assert d["factor_width"] == "bf16"
    xref = np.linalg.solve(A, b[..., None])[..., 0]
    assert _dev(x, xref) < 1e-6


# ---------------------------------------------------------------------------
# exec-cache identity: a mixed program is never served for an f64 request
# ---------------------------------------------------------------------------

def test_exec_cache_key_distinct_per_precision_mode():
    from raft_tpu.parallel import exec_cache

    def key():
        return exec_cache.make_key(fn="sweep_cases", model="sha256:aa",
                                   nw=10)

    base = key()
    assert base == key()                              # stable
    _config.set_precision_mode("mixed")
    k_mixed = key()
    _config.set_precision_width("bf16")
    k_bf16 = key()
    _config.set_precision_width(None)
    _config.set_precision_mode("f32")
    k_f32 = key()
    _config.set_precision_mode(None)
    assert len({base, k_mixed, k_bf16, k_f32}) == 4


def test_exec_cache_key_distinct_per_promote_tol(monkeypatch):
    from raft_tpu.parallel import exec_cache

    _config.set_precision_mode("mixed")
    k1 = exec_cache.make_key(fn="sweep_cases", model="sha256:aa", nw=10)
    monkeypatch.setenv("RAFT_TPU_PRECISION_TOL", "1e-7")
    k2 = exec_cache.make_key(fn="sweep_cases", model="sha256:aa", nw=10)
    assert k1 != k2


@pytest.fixture(scope="module")
def fowt():
    from raft_tpu.io.designs import load_design
    from raft_tpu.models.fowt import build_fowt

    design = load_design("OC3spar")
    w = np.arange(0.05, 0.25, 0.05) * 2 * np.pi     # 4 coarse bins
    return build_fowt(design, w,
                      depth=float(design["site"]["water_depth"]))


def test_sweep_warm_hit_per_precision_mode(fowt, tmp_path, monkeypatch):
    """Acceptance: per-mode cache identity end to end.  An f64 sweep and
    a mixed sweep each cold-compile their OWN executable (the mixed
    request must not be served the f64 program, nor vice versa), and
    each re-run is a span-asserted warm hit that skips lower+compile."""
    from raft_tpu import obs
    from raft_tpu.parallel import exec_cache
    from raft_tpu.parallel.sweep import sweep_cases

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path))
    exec_cache.reset_stats()
    Hs = np.array([3.0, 6.0])
    Tp = np.array([8.0, 10.0])
    beta = np.zeros(2)

    out_f64 = sweep_cases(fowt, Hs, Tp, beta, nIter=2)
    assert exec_cache.stats()["misses"] == 1          # f64 cold

    _config.set_precision_mode("mixed")
    obs.reset_all()
    out_mixed = sweep_cases(fowt, Hs, Tp, beta, nIter=2)
    st = exec_cache.stats()
    assert st["misses"] == 2 and st["hits"] == 0      # mixed is NOT f64
    agg = obs.aggregate()
    assert agg["sweep_lower"][1] == 1                 # really compiled

    obs.reset_all()
    sweep_cases(fowt, Hs, Tp, beta, nIter=2)          # mixed warm
    agg = obs.aggregate()
    assert "sweep_lower" not in agg and "sweep_compile" not in agg
    assert exec_cache.stats()["hits"] == 1

    _config.set_precision_mode(None)
    obs.reset_all()
    sweep_cases(fowt, Hs, Tp, beta, nIter=2)          # f64 warm
    agg = obs.aggregate()
    assert "sweep_lower" not in agg and "sweep_compile" not in agg
    assert exec_cache.stats()["hits"] == 2

    # physics: the mixed ladder holds the ledger bar on the real sweep
    assert _dev(np.asarray(out_mixed["std"]),
                np.asarray(out_f64["std"])) < 1e-6
