"""Prometheus text-exposition conformance of ``GET /metrics``.

One checker, three producers: the registry's ``exposition()`` page
itself, the replica server (``tools/raftserve.py``) and the fleet
router (``raft_tpu.serve.router``).  Guards the contract a real
Prometheus scraper relies on: every sample belongs to a ``# TYPE``-
declared family, counter families end in ``_total``, histogram series
carry a ``+Inf`` bucket with cumulative counts matching ``_count``,
and label values survive escaping round-trips.
"""
import threading
import urllib.request

import pytest

from raft_tpu.obs import metrics as M
from raft_tpu.obs.trendstore import parse_prometheus

_SUFFIXES = ("_bucket", "_sum", "_count")


def check_exposition(text: str) -> dict:
    """Assert exposition-format (0.0.4) conformance; returns
    {family: kind}."""
    import re

    sample_re = re.compile(
        r"^([A-Za-z_:][A-Za-z0-9_:]*)"
        r"(\{[A-Za-z0-9_]+=\"(?:[^\"\\\n]|\\[\\\"n])*\""
        r"(?:,[A-Za-z0-9_]+=\"(?:[^\"\\\n]|\\[\\\"n])*\")*\})?"
        r" (-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|[+-]Inf|NaN)$")
    families: dict[str, str] = {}
    hist: dict[tuple, dict] = {}
    for line in text.splitlines():
        assert line == line.rstrip(), f"trailing whitespace: {line!r}"
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = kind
            continue
        if line.startswith("# HELP "):
            assert "\n" not in line and line.count("# HELP ") == 1
            continue
        if not line or line.startswith("#"):
            continue                      # legal comment noise
        m = sample_re.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels, _val = m.group(1), m.group(2) or "", m.group(3)
        fam = name
        if name.endswith(_SUFFIXES):
            base = name.rsplit("_", 1)[0]
            if families.get(base) == "histogram":
                fam = base
        assert fam in families, f"sample {name!r} has no # TYPE line"
        if families[fam] == "counter":
            assert fam.endswith("_total"), \
                f"counter family {fam!r} must end in _total"
        if families[fam] == "histogram":
            pairs = dict(re.findall(
                r'([A-Za-z0-9_]+)="((?:[^"\\]|\\.)*)"', labels))
            le = pairs.pop("le", None)
            serie = hist.setdefault(
                (fam, tuple(sorted(pairs.items()))), {})
            if name.endswith("_bucket"):
                assert le is not None, f"bucket without le=: {line!r}"
                serie.setdefault("buckets", []).append(
                    (le, float(_val)))
            else:
                serie[name.rsplit("_", 1)[1]] = float(_val)
    for (fam, _labels), serie in hist.items():
        buckets = serie.get("buckets", [])
        assert buckets and buckets[-1][0] == "+Inf", \
            f"{fam}: histogram series missing +Inf bucket"
        counts = [c for _, c in buckets]
        assert counts == sorted(counts), \
            f"{fam}: bucket counts not cumulative"
        assert serie.get("count") == counts[-1], \
            f"{fam}: _count != +Inf bucket"
        assert "sum" in serie, f"{fam}: missing _sum sample"
    return families


NASTY = 'quote:" slash:\\ newline:\nend'


@pytest.fixture()
def populated_registry():
    """Representative samples of every metric kind, including the
    solve-health and devprof gauges and a label value that needs all
    three escapes."""
    M.record_solve_health("sweep", 2.5e-10, 1e-10, 0,
                          cond_max=12.0, iters_max=4)
    M.record_devprof({"kernel": "conftest_kernel", "compile_s": 0.5,
                      "flops": 1e9, "bytes_accessed": 5e8,
                      "arithmetic_intensity": 2.0,
                      "argument_bytes": 64})
    M.counter("raft_solve_dispatch_total",
              "solver dispatches").inc(1.0, backend="cpu", n="4",
                                       fused="1")
    M.histogram("raft_tpu_serve_request_latency_s",
                "request latency").observe(0.123, tenant="t0")
    M.histogram("raft_tpu_serve_request_latency_s").observe(7.0,
                                                            tenant="t0")
    M.gauge("raft_tpu_build_info", "build facts").set(1.0, note=NASTY)


def test_registry_exposition_conforms(populated_registry):
    text = M.exposition(run_id="conformance-test")
    families = check_exposition(text)
    assert families["raft_tpu_solve_residual_rel"] == "gauge"
    assert families["raft_tpu_devprof_compile_seconds"] == "gauge"
    assert families["raft_solve_dispatch_total"] == "counter"
    assert families["raft_tpu_serve_request_latency_s"] == "histogram"
    # identity header precedes the samples as a plain comment
    assert text.startswith("# raft_tpu exposition pid=")
    assert "run_id=conformance-test" in text.splitlines()[0]
    # escaping round-trips through an independent parser
    parsed = parse_prometheus(text)
    (labels, value) = parsed["raft_tpu_build_info"][0]
    assert labels["note"] == NASTY
    assert value == 1.0


def _scrape(srv) -> tuple[str, str]:
    """serve_forever in a daemon thread, GET /metrics once, shut down."""
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return (resp.read().decode(),
                    resp.headers.get("Content-Type", ""))
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(5)


class _DummyService:
    """Just enough surface for the non-/metrics endpoints."""

    def stats(self):
        return {"queued": 0}

    def summary(self):
        return {"ok": True}


def test_replica_server_metrics_endpoint(populated_registry):
    from tools.raftserve import make_serve_server

    text, ctype = _scrape(make_serve_server(_DummyService(), port=0))
    assert ctype == "text/plain; version=0.0.4"
    families = check_exposition(text)
    assert "raft_tpu_solve_residual_rel" in families


def test_router_metrics_endpoint(populated_registry):
    from raft_tpu.serve.router import ReplicaRouter, make_server

    # the router is never start()ed: no health sweeps, no backends
    # contacted — /metrics must still serve this process's registry
    router = ReplicaRouter(["http://127.0.0.1:1/"])
    text, ctype = _scrape(make_server(router, port=0))
    assert ctype == "text/plain; version=0.0.4"
    families = check_exposition(text)
    assert "raft_tpu_devprof_compile_seconds" in families
