"""QTF engine validation.

Ground truth comes from three directions:
1. Kernel parity: the reference's helpers.py imports standalone (no
   moorpy/ccblade), so the gradient/2nd-order-potential kernels are
   compared against the ACTUAL reference functions at beta=0 (the heading
   where the reference's mixed deg/rad convention and its grad[2][1]
   index quirk are both inert — see ops/waves.py docstrings).
2. A serial numpy QTF assembled node-by-node with the reference helper
   functions (mirroring raft_fowt.py:1437-1640) on a small spar model,
   compared against the vectorized double-vmap engine.
3. Analytic identities for the difference-frequency force sums and the
   .12d round trip.
"""
import importlib.util
import os

import numpy as np
import pytest
from numpy.testing import assert_allclose

from raft_tpu.models.fowt import build_fowt, fowt_pose, fowt_statics
from raft_tpu.models import qtf as qt
from raft_tpu.ops import waves

REF_HELPERS = "/root/reference/raft/helpers.py"


@pytest.fixture(scope="module")
def ref():
    if not os.path.isfile(REF_HELPERS):
        pytest.skip("reference helpers not available")
    spec = importlib.util.spec_from_file_location("ref_helpers", REF_HELPERS)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


# --------------------------------------------------------------------------
# 1. kernel parity vs the reference functions (beta = 0)
# --------------------------------------------------------------------------

def test_grad_u_parity(ref):
    h = 200.0
    for w, k, r in [(0.5, 0.0255, [3.0, -2.0, -8.0]),
                    (1.2, 0.1468, [-5.0, 1.0, -2.5]),
                    (2.0, 0.4077, [0.0, 0.0, -15.0])]:
        mine = np.asarray(waves.wave_vel_gradient(w, k, 0.0, h, np.array(r)))
        theirs = ref.getWaveKin_grad_u1(w, k, 0.0, h, np.array(r))
        assert_allclose(mine, theirs, rtol=1e-12, err_msg=f"w={w}")


def test_grad_pres_parity(ref):
    h = 150.0
    for k, r in [(0.0255, [3.0, -2.0, -8.0]), (0.4077, [1.0, 2.0, -30.0])]:
        mine = np.asarray(waves.wave_pres1st_gradient(k, 0.0, h, np.array(r)))
        theirs = ref.getWaveKin_grad_pres1st(k, 0.0, h, np.array(r))
        assert_allclose(mine, theirs, rtol=1e-12)


def test_pot2nd_parity(ref):
    h = 200.0
    w1, w2 = 0.6, 0.9
    k1 = float(np.asarray(waves.wave_number(w1, h)))
    k2 = float(np.asarray(waves.wave_number(w2, h)))
    r = np.array([4.0, -1.0, -12.0])
    acc_m, p_m = waves.wave_pot_2nd_order(w1, w2, k1, k2, 0.0, 0.0, h, r)
    acc_r, p_r = ref.getWaveKin_pot2ndOrd(w1, w2, k1, k2, 0.0, 0.0, h, r)
    assert_allclose(np.asarray(acc_m), acc_r, rtol=1e-10)
    assert_allclose(complex(p_m), p_r, rtol=1e-10)
    # equal frequencies -> exactly zero
    acc_m, p_m = waves.wave_pot_2nd_order(w1, w1, k1, k1, 0.0, 0.0, h, r)
    assert np.all(np.asarray(acc_m) == 0) and complex(p_m) == 0


# --------------------------------------------------------------------------
# 2. serial reference-style QTF vs the vectorized engine
# --------------------------------------------------------------------------

def _mini_design():
    return {
        "site": {"water_depth": 200.0, "rho_water": 1025.0, "g": 9.81},
        "platform": {
            "potModMaster": 1,
            "potSecOrder": 1,
            "min_freq2nd": 0.04, "max_freq2nd": 0.12, "df_freq2nd": 0.02,
            "members": [{
                "name": "spar", "type": 2,
                "rA": [0, 0, -20], "rB": [0, 0, 10],
                "shape": "circ", "gamma": 0.0, "potMod": False,
                "stations": [0, 0.5, 1], "d": [10.0, 8.0, 8.0],
                "t": 0.05, "Cd": 0.6, "Ca": 0.97,
                "CdEnd": 0.6, "CaEnd": 0.6, "rho_shell": 7850.0,
                "dlsMax": 5.0,
            }],
        },
    }


def _serial_qtf(fowt, pose, beta, Xi0, M_struc, ref):
    """Straight per-node/per-pair transcription of the reference QTF loop
    (raft_fowt.py:1437-1640) using the reference's own helper kernels."""
    w2, k2 = fowt.w1_2nd, fowt.k1_2nd
    nw2 = len(w2)
    h, rho, g = fowt.depth, fowt.rho_water, fowt.g

    Xi = np.zeros((6, nw2), dtype=complex)
    for i in range(6):
        Xi[i] = (np.interp(w2, fowt.w, Xi0[i].real, left=0, right=0)
                 + 1j * np.interp(w2, fowt.w, Xi0[i].imag, left=0, right=0))
    F1st = np.zeros((6, nw2), dtype=complex)
    F1st[0:3] = M_struc[0, 0] * (-w2**2 * Xi[0:3])
    F1st[3:6] = M_struc[3:, 3:] @ (-w2**2 * Xi[3:])

    qtf = np.zeros((nw2, nw2, 6), dtype=complex)
    for i1 in range(nw2):
        for i2 in range(i1, nw2):
            F_rotN = np.zeros(6, dtype=complex)
            F_rotN[0:3] = 0.25 * (np.cross(Xi[3:, i1], np.conj(F1st[0:3, i2]))
                                  + np.cross(np.conj(Xi[3:, i2]), F1st[0:3, i1]))
            F_rotN[3:] = 0.25 * (np.cross(Xi[3:, i1], np.conj(F1st[3:, i2]))
                                 + np.cross(np.conj(Xi[3:, i2]), F1st[3:, i1]))
            qtf[i1, i2] = F_rotN

    nd = fowt.nodes
    r_all = np.asarray(pose["r"])
    rPRP = np.asarray(pose["r6"])[:3]
    for im, m in enumerate(fowt.members):
        sel = np.where(np.asarray(nd.member_index) == im)[0]
        rm = r_all[sel]
        if rm[0, 2] > 0 and rm[-1, 2] > 0:
            continue
        q = np.asarray(pose["q"])[sel[0]]
        p1 = np.asarray(pose["p1"])[sel[0]]
        p2 = np.asarray(pose["p2"])[sel[0]]
        qMat, p1Mat, p2Mat = np.outer(q, q), np.outer(p1, p1), np.outer(p2, p2)

        ns = len(sel)
        u = np.zeros((3, nw2, ns), dtype=complex)
        nodeV = np.zeros((3, nw2, ns), dtype=complex)
        dr = np.zeros((3, nw2, ns), dtype=complex)
        nodeV_ax = np.zeros((nw2, ns), dtype=complex)
        grad_u = np.zeros((3, 3, nw2, ns), dtype=complex)
        grad_du = np.zeros((3, 3, nw2, ns), dtype=complex)
        grad_p = np.zeros((3, nw2, ns), dtype=complex)
        for iN in range(ns):
            rr = rm[iN]
            dr[:, :, iN], nodeV[:, :, iN], _ = ref.getKinematics(rr - rPRP, Xi, w2)
            u[:, :, iN], _, _ = ref.getWaveKin(np.ones(nw2), beta, w2, k2, h,
                                               rr, nw2, rho=rho, g=g)
            for iw in range(nw2):
                grad_u[:, :, iw, iN] = ref.getWaveKin_grad_u1(w2[iw], k2[iw], beta, h, rr)
                grad_du[:, :, iw, iN] = ref.getWaveKin_grad_dudt(w2[iw], k2[iw], beta, h, rr)
                nodeV_ax[iw, iN] = np.dot(u[:, iw, iN] - nodeV[:, iw, iN], q)
                grad_p[:, iw, iN] = ref.getWaveKin_grad_pres1st(k2[iw], beta, h, rr,
                                                                rho=rho, g=g)

        # waterline fields
        crossing = rm[-1, 2] * rm[0, 2] < 0
        if crossing:
            r_int = rm[0] + (rm[-1] - rm[0]) * (0.0 - rm[0, 2]) / (rm[-1, 2] - rm[0, 2])
            _, ud_wl, eta = ref.getWaveKin(np.ones(nw2), beta, w2, k2, h, r_int,
                                           nw2, rho=1, g=1)
            dr_wl, _, a_wl = ref.getKinematics(r_int - rPRP, Xi, w2)
            eta_r = eta - dr_wl[2, :]
            i_wl = np.where(rm[:, 2] < 0)[0][-1]
            if i_wl != len(m.ds) - 1:
                d_wl = 0.5 * (m.ds[i_wl] + m.ds[i_wl + 1])
            else:
                d_wl = m.ds[i_wl]
            a_wl_area = 0.25 * np.pi * d_wl**2
            g_e1 = np.zeros((3, nw2), dtype=complex)
            for iw in range(nw2):
                g_e1[:, iw] = -g * (np.cross(Xi[3:, iw], p1)[2] * p1
                                    + np.cross(Xi[3:, iw], p2)[2] * p2)

        for i1 in range(nw2):
            for i2 in range(i1, nw2):
                w1v, w2v, k1v, k2v = w2[i1], w2[i2], k2[i1], k2[i2]
                F = {k: np.zeros(6, dtype=complex)
                     for k in ("pot", "conv", "axdv", "eta", "nabla", "rslb")}
                for iN in range(ns):
                    if rm[iN, 2] >= 0:
                        continue
                    n = sel[iN]
                    Ca_p1, Ca_p2, Ca_End = nd.Ca_p1[n], nd.Ca_p2[n], nd.Ca_End[n]
                    dls = nd.dls[n]
                    z = rm[iN, 2]
                    v_i = nd.v_side[n]
                    if z + 0.5 * dls > 0:
                        v_i = v_i * (0.5 * dls - z) / dls
                    Minert = (1 + Ca_p1) * p1Mat + (1 + Ca_p2) * p2Mat
                    CaM = Ca_p1 * p1Mat + Ca_p2 * p2Mat

                    acc2, p2nd = ref.getWaveKin_pot2ndOrd(w1v, w2v, k1v, k2v,
                                                          beta, beta, h, rm[iN],
                                                          g=g, rho=rho)
                    f_pot = rho * v_i * (Minert @ acc2)
                    conv = 0.25 * (grad_u[:, :, i1, iN] @ np.conj(u[:, i2, iN])
                                   + np.conj(grad_u[:, :, i2, iN]) @ u[:, i1, iN])
                    f_conv = rho * v_i * (Minert @ conv)
                    f_axdv = rho * v_i * (CaM @ ref.getWaveKin_axdivAcc(
                        w1v, w2v, k1v, k2v, beta, beta, h, rm[iN],
                        nodeV[:, i1, iN].copy(), nodeV[:, i2, iN].copy(), q, g=g))
                    accn = (0.25 * grad_du[:, :, i1, iN] @ np.conj(dr[:, i2, iN])
                            + 0.25 * np.conj(grad_du[:, :, i2, iN]) @ dr[:, i1, iN])
                    f_nab = rho * v_i * (Minert @ accn)
                    OM1 = -ref.getH(1j * w1v * Xi[3:, i1])
                    OM2 = -ref.getH(1j * w2v * Xi[3:, i2])
                    f_rslb = -0.25 * 2 * (CaM @ (OM1 @ np.conj(nodeV_ax[i2, iN] * q)
                                                 + np.conj(OM2) @ (nodeV_ax[i1, iN] * q)))
                    f_rslb = f_rslb * rho * v_i
                    u1a = u[:, i1, iN] - nodeV[:, i1, iN]
                    u2a = u[:, i2, iN] - nodeV[:, i2, iN]
                    V1 = grad_u[:, :, i1, iN] + OM1
                    V2 = grad_u[:, :, i2, iN] + OM2
                    aux = 0.25 * (V1 @ np.conj(CaM @ u2a) + np.conj(V2) @ (CaM @ u1a))
                    aux = aux - qMat @ aux
                    f_rslb = f_rslb + rho * v_i * aux
                    u1a = u1a - qMat @ u1a
                    u2a = u2a - qMat @ u2a
                    aux = 0.25 * (CaM @ (V1 @ np.conj(u2a)) + CaM @ (np.conj(V2) @ u1a))
                    f_rslb = f_rslb - rho * v_i * aux

                    v_e, a_ie = nd.v_end[n], nd.a_i[n]
                    f_pot = f_pot + a_ie * p2nd * q
                    f_pot = f_pot + rho * v_e * Ca_End * (qMat @ acc2)
                    f_conv = f_conv + rho * v_e * Ca_End * (qMat @ conv)
                    f_nab = f_nab + rho * v_e * Ca_End * (qMat @ accn)
                    p_nab = (0.25 * np.dot(grad_p[:, i1, iN], np.conj(dr[:, i2, iN]))
                             + 0.25 * np.dot(np.conj(grad_p[:, i2, iN]), dr[:, i1, iN]))
                    f_nab = f_nab + a_ie * p_nab * q
                    p_drop = -2 * 0.25 * 0.5 * rho * np.dot(
                        (p1Mat + p2Mat) @ u1a_raw(u, nodeV, i1, iN),
                        np.conj(CaM @ u1a_raw(u, nodeV, i2, iN)))
                    f_conv = f_conv + a_ie * p_drop * q

                    off = rm[iN] - rPRP
                    for key, fv in (("pot", f_pot), ("conv", f_conv),
                                    ("axdv", f_axdv), ("nabla", f_nab),
                                    ("rslb", f_rslb)):
                        F[key] += np.r_[fv, np.cross(off, fv)]

                if crossing:
                    n_last = sel[-1]
                    Ca_p1, Ca_p2 = nd.Ca_p1[n_last], nd.Ca_p2[n_last]
                    Minert = (1 + Ca_p1) * p1Mat + (1 + Ca_p2) * p2Mat
                    CaM = Ca_p1 * p1Mat + Ca_p2 * p2Mat
                    f_eta = 0.25 * (ud_wl[:, i1] * np.conj(eta_r[i2])
                                    + np.conj(ud_wl[:, i2]) * eta_r[i1])
                    f_eta = rho * a_wl_area * (Minert @ f_eta)
                    a_eta = 0.25 * (a_wl[:, i1] * np.conj(eta_r[i2])
                                    + np.conj(a_wl[:, i2]) * eta_r[i1])
                    f_eta = f_eta - rho * a_wl_area * (CaM @ a_eta)
                    f_eta = f_eta - 0.25 * rho * a_wl_area * (
                        g_e1[:, i1] * np.conj(eta_r[i2])
                        + np.conj(g_e1[:, i2]) * eta_r[i1])
                    off = r_int - rPRP
                    F["eta"] = np.r_[f_eta, np.cross(off, f_eta)]

                qtf[i1, i2] += sum(F.values())

    for i in range(6):
        qtf[:, :, i] = (qtf[:, :, i] + np.conj(qtf[:, :, i]).T
                        - np.diag(np.diag(np.conj(qtf[:, :, i]))))
    return qtf


def u1a_raw(u, nodeV, i, iN):
    return u[:, i, iN] - nodeV[:, i, iN]


def test_qtf_engine_vs_serial_reference(ref):
    design = _mini_design()
    w = np.arange(0.02, 0.25, 0.02) * 2 * np.pi
    fowt = build_fowt(design, w, depth=200.0)
    pose = fowt_pose(fowt, np.zeros(6))
    stat = fowt_statics(fowt, pose)
    M_struc = np.asarray(stat["M_struc"])

    rng = np.random.default_rng(3)
    Xi0 = (rng.normal(size=(6, len(w))) + 1j * rng.normal(size=(6, len(w))))
    Xi0[3:] *= 0.01   # rotations small

    mine = np.asarray(qt.calc_qtf_slender_body(fowt, pose, 0.0, Xi0=Xi0,
                                               M_struc=M_struc))
    serial = _serial_qtf(fowt, pose, 0.0, Xi0, M_struc, ref)
    assert mine.shape == serial.shape == (5, 5, 6)
    assert_allclose(mine, serial, rtol=1e-7, atol=1e-3)


def test_qtf_hermitian(ref):
    design = _mini_design()
    w = np.arange(0.02, 0.25, 0.02) * 2 * np.pi
    fowt = build_fowt(design, w, depth=200.0)
    pose = fowt_pose(fowt, np.zeros(6))
    Q = np.asarray(qt.calc_qtf_slender_body(fowt, pose, 0.0))
    for i in range(6):
        assert_allclose(Q[:, :, i], np.conj(Q[:, :, i]).T, rtol=1e-12,
                        atol=1e-10)


# --------------------------------------------------------------------------
# 3. difference-frequency force sums + .12d I/O
# --------------------------------------------------------------------------

def test_hydro_force_2nd_constant_qtf():
    """With a constant real QTF on the model grid, the sums have closed
    forms (reference: raft_fowt.py:1786-1804)."""
    nw = 20
    w = np.linspace(0.1, 2.0, nw)
    dw = w[1] - w[0]
    S0 = np.exp(-((w - 1.0) / 0.3) ** 2)
    Q0 = 3.0
    qtf = np.full((nw, nw, 1, 6), Q0, dtype=complex)
    f_mean, f = qt.hydro_force_2nd(qtf, [0.0], w, 0.0, S0, w)
    f_mean, f = np.asarray(f_mean), np.asarray(f)
    assert_allclose(f_mean, 2 * Q0 * np.sum(S0) * dw * np.ones(6), rtol=1e-10)
    # direct loop for one difference frequency (pre-shift imu=2 lands at
    # index 1 after the one-bin shift)
    imu = 2
    expect = 4 * np.sqrt(np.sum(S0[:-imu] * S0[imu:] * Q0**2)) * dw
    assert_allclose(f[0, imu - 1], expect, rtol=1e-10)
    assert f[0, -1] == 0.0


def test_hydro_force_2nd_spectrum_mode_direct_loop():
    """'spectrum' mode against a literal transcription of the reference's
    per-difference-frequency loop (raft_fowt.py:1760-1784)."""
    nw = 40
    w = np.linspace(0.05, 2.0, nw)
    dw = w[1] - w[0]
    S0 = 5.0 * np.exp(-((w - 0.8) / 0.2) ** 2)
    nw2 = 15
    w2 = np.linspace(0.2, 1.8, nw2)
    dw2 = w2[1] - w2[0]
    rng = np.random.default_rng(5)
    A = rng.normal(size=(nw2, nw2, 1, 6)) + 1j * rng.normal(size=(nw2, nw2, 1, 6))
    qtf = A + np.conj(np.swapaxes(A, 0, 1))   # Hermitian
    fm_s, f_s = (np.asarray(x) for x in
                 qt.hydro_force_2nd(qtf, [0.0], w2, 0.0, S0, w, "spectrum"))

    S2 = np.interp(w2, w, S0, left=0, right=0)
    mu = w2 - w2[0]
    f_exp = np.zeros((6, nw))
    fm_exp = np.zeros(6)
    for idof in range(6):
        Q = qtf[:, :, 0, idof]
        Sf = np.zeros(nw2)
        for imu in range(1, nw2):
            Saux = np.zeros(nw2)
            Saux[0:nw2 - imu] = S2[imu:]
            Qaux = np.zeros(nw2, dtype=complex)
            Qaux[0:nw2 - imu] = np.diag(Q, imu)
            Sf[imu] = 8 * np.sum(S2 * Saux * np.abs(Qaux) ** 2) * dw2
        fm_exp[idof] = 2 * np.sum(S2 * np.diag(Q.real)) * dw2
        Sf_i = np.interp(w - w[0], mu, Sf, left=0, right=0)
        f_exp[idof] = np.sqrt(2 * Sf_i * dw)
    f_exp[:, 0:-1] = f_exp[:, 1:]
    f_exp[:, -1] = 0
    assert_allclose(fm_s, fm_exp, rtol=1e-10)
    assert_allclose(f_s, f_exp, rtol=1e-10, atol=1e-12)


def test_12d_roundtrip(tmp_path):
    nw2 = 6
    w2 = np.linspace(0.3, 1.5, nw2)
    rng = np.random.default_rng(11)
    A = rng.normal(size=(nw2, nw2, 1, 6)) + 1j * rng.normal(size=(nw2, nw2, 1, 6))
    qtf = (A + np.conj(np.swapaxes(A, 0, 1))) * 1e3
    path = str(tmp_path / "test.12d")
    qt.write_qtf_12d(path, qtf, w2, [0.0])
    back = qt.read_qtf_12d(path)
    assert_allclose(back.w, w2, rtol=1e-3)
    assert_allclose(back.qtf[:, :, 0, :], qtf[:, :, 0, :], rtol=2e-4, atol=1e-3)


@pytest.mark.slow
def test_qtf_vs_reference_fowt_oracle():
    """The engine vs the ACTUAL reference calcQTF_slenderBody, executed on
    the stubbed reference FOWT (tests/ref_oracle.py) for the OC4semi
    potModMaster=1 design — closing the loop the serial transcription
    (test_qtf_engine_vs_serial_reference) leaves open: here the ASSEMBLY
    logic is the reference's own code, not a re-reading of it.  A smooth
    synthetic RAO exercises every motion-dependent term."""
    import yaml

    path = "/root/reference/examples/OC4semi-RAFT_QTF.yaml"
    if not os.path.isfile(path):
        pytest.skip("reference example not available")
    from ref_oracle import build_reference_fowt_from_yaml

    OVR_S = {"min_freq": 0.005, "max_freq": 0.25}
    OVR_P = {"min_freq2nd": 0.04, "df_freq2nd": 0.03, "max_freq2nd": 0.30,
             "outFolderQTF": None}
    ref_fowt, w, d = build_reference_fowt_from_yaml(
        path, settings_overrides=OVR_S, platform_overrides=OVR_P)
    ref_fowt.outFolderQTF = None        # no .12d side-writes
    case = dict(zip(d["cases"]["keys"], d["cases"]["data"][0]))
    ref_fowt.setPosition(np.zeros(6))
    ref_fowt.calcStatics()
    ref_fowt.calcHydroConstants()
    ref_fowt.calcHydroExcitation(case, memberList=ref_fowt.memberList)

    # deterministic smooth synthetic RAO on the model grid
    rng = np.random.default_rng(7)
    amp = np.array([1.0, 0.3, 0.8, 0.01, 0.02, 0.005])
    Xi0 = np.zeros((6, len(w)), dtype=complex)
    for i in range(6):
        envelope = np.exp(-((w - 0.5 - 0.05 * i) / 0.35) ** 2)
        Xi0[i] = amp[i] * envelope * np.exp(1j * (0.4 * i + w))

    ref_fowt.calcQTF_slenderBody(waveHeadInd=0, Xi0=Xi0, verbose=False)
    ref_qtf = np.asarray(ref_fowt.qtf)[:, :, 0, :]   # (nw2, nw2, 6)

    # ours on the same design via Model (same prep path)
    from raft_tpu.model import Model

    design = yaml.safe_load(open(path))
    design["settings"].update(OVR_S)
    design["platform"].update(OVR_P)
    fowt = Model(design).fowtList[0]
    assert_allclose(fowt.w1_2nd, ref_fowt.w1_2nd, rtol=1e-12)
    pose = fowt_pose(fowt, np.zeros(6))
    stat = fowt_statics(fowt, pose)
    ours = np.asarray(qt.calc_qtf_slender_body(
        fowt, pose, 0.0, Xi0=Xi0, M_struc=np.asarray(stat["M_struc"])))

    scale = np.abs(ref_qtf).max(axis=(0, 1))
    for idof in range(6):
        assert_allclose(ours[:, :, idof], ref_qtf[:, :, idof],
                        atol=2e-5 * scale[idof], rtol=2e-5,
                        err_msg=f"DOF {idof}")


@pytest.mark.slow
def test_oc4semi_internal_qtf_end_to_end():
    """OC4semi with potSecOrder=1: internal slender-body QTF feeds the
    dynamics + mean-drift statics re-solve (reference example-RAFT_QTF)."""
    import yaml
    from raft_tpu.model import Model

    path = "/root/reference/examples/OC4semi-RAFT_QTF.yaml"
    if not os.path.isfile(path):
        pytest.skip("reference example not available")
    design = yaml.safe_load(open(path))
    # coarse grids for test speed
    design["settings"]["min_freq"] = 0.005
    design["settings"]["max_freq"] = 0.25
    design["platform"]["min_freq2nd"] = 0.04
    design["platform"]["df_freq2nd"] = 0.03
    design["platform"]["max_freq2nd"] = 0.30

    m = Model(design)
    res = m.analyzeCases()
    met = res["case_metrics"][0][0]
    assert np.all(np.isfinite(met["surge_PSD"]))
    state = m._state[0]
    # slow-drift forces present and mean surge drift positive for 0-deg waves
    assert state["Fhydro_2nd"].shape[0] >= 1
    assert np.any(state["Fhydro_2nd"][0, 0, :] > 0)
    assert state["Fhydro_2nd_mean"][0, 0] > 0
    # the statics re-solve with mean drift must move the mean surge offset
    # downwave (positive x)
    assert res["mean_offsets"][0][0] > 0.05


@pytest.mark.slow
def test_internal_qtf_multi_heading():
    """potSecOrder==1 with two wave headings: each heading gets its own
    QTF from its own RAOs (reference: raft_model.py:1066-1083), so the
    heading-90 slow-drift force must push in +y, not +x."""
    import yaml
    from raft_tpu.model import Model

    path = "/root/reference/examples/OC4semi-RAFT_QTF.yaml"
    if not os.path.isfile(path):
        pytest.skip("reference example not available")
    design = yaml.safe_load(open(path))
    design["settings"]["min_freq"] = 0.01
    design["settings"]["max_freq"] = 0.25
    design["platform"]["min_freq2nd"] = 0.05
    design["platform"]["df_freq2nd"] = 0.05
    design["platform"]["max_freq2nd"] = 0.25
    keys = design["cases"]["keys"]
    row = list(design["cases"]["data"][0])
    ih_head = keys.index("wave_heading")
    row[ih_head] = [0.0, 90.0]
    case = dict(zip(keys, row))

    m = Model(design)
    m.solveStatics(case)
    m.solveDynamics(case)
    state = m._state[0]
    mean = state["Fhydro_2nd_mean"]
    assert mean.shape[0] == 2
    assert np.all(np.isfinite(mean)) and np.all(np.isfinite(state["Fhydro_2nd"]))
    # heading 0 drift is downwave on this platform
    assert mean[0, 0] > 0 and abs(mean[0, 0]) > abs(mean[0, 1])
    # heading 90 must NOT reuse the heading-0 QTF: its force amplitudes
    # differ and excite sway rather than surge
    f0, f1 = state["Fhydro_2nd"][0], state["Fhydro_2nd"][1]
    assert not np.allclose(f1, f0, rtol=1e-3)
    assert np.abs(f1[1]).max() > np.abs(f1[0]).max()


def test_qtf_rotational_equivariance():
    """Rotating the wave heading AND the motion RAOs by 90 deg about z
    must rotate the QTF force vector exactly — a strong check on heading
    conventions across every term of the engine."""
    design = _mini_design()
    w = np.arange(0.02, 0.25, 0.02) * 2 * np.pi
    fowt = build_fowt(design, w, depth=200.0)
    pose = fowt_pose(fowt, np.zeros(6))
    M = np.asarray(fowt_statics(fowt, pose)["M_struc"])
    rng = np.random.default_rng(3)
    Xi0 = rng.normal(size=(6, len(w))) + 1j * rng.normal(size=(6, len(w)))
    Xi0[3:] *= 0.01
    R = np.array([[0, -1, 0], [1, 0, 0], [0, 0, 1]], float)
    Xi90 = np.concatenate([np.einsum("ij,jw->iw", R, Xi0[:3]),
                           np.einsum("ij,jw->iw", R, Xi0[3:])])
    Q0 = np.asarray(qt.calc_qtf_slender_body(fowt, pose, 0.0, Xi0=Xi0,
                                             M_struc=M))
    Q90 = np.asarray(qt.calc_qtf_slender_body(fowt, pose, np.pi / 2,
                                              Xi0=Xi90, M_struc=M))
    F0 = Q0.reshape(-1, 6).T
    F90 = Q90.reshape(-1, 6).T
    F0r = np.vstack([np.einsum("ij,jn->in", R, F0[:3]),
                     np.einsum("ij,jn->in", R, F0[3:])])
    assert_allclose(F90, F0r, rtol=1e-10, atol=1e-8)


@pytest.mark.slow
def test_oc4semi_external_qtf_end_to_end():
    """OC4semi with potSecOrder=2: .12d file drives the 2nd-order forces."""
    import yaml
    from raft_tpu.model import Model

    path = "/root/reference/examples/OC4semi-WAMIT_Coefs.yaml"
    hydro = "/root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi"
    if not (os.path.isfile(path) and os.path.isfile(hydro + ".12d")):
        pytest.skip("reference example not available")
    design = yaml.safe_load(open(path))
    design["platform"]["hydroPath"] = hydro
    design["settings"]["min_freq"] = 0.005
    design["settings"]["max_freq"] = 0.25

    m = Model(design)
    res = m.analyzeCases()
    met = res["case_metrics"][0][0]
    assert np.all(np.isfinite(met["surge_PSD"]))
    state = m._state[0]
    assert np.any(np.abs(state["Fhydro_2nd"][0]) > 0)


def test_read_reference_12d():
    path = "/root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi.12d"
    if not os.path.isfile(path):
        pytest.skip("reference .12d not available")
    d = qt.read_qtf_12d(path)
    assert d.qtf.shape[0] == d.qtf.shape[1] == len(d.w)
    assert np.all(np.isfinite(d.qtf))
    for i in range(6):
        assert_allclose(d.qtf[:, :, 0, i], np.conj(d.qtf[:, :, 0, i]).T,
                        atol=1e-6 * np.abs(d.qtf).max())


def test_out_folder_qtf_snapshot_and_resume(tmp_path):
    """outFolderQTF (reference: raft_fowt.py:255-257): the internal-QTF
    run drops .4 RAO and .12d QTF snapshots, and a re-run with unchanged
    inputs reloads the QTF from the folder (checkpoint/resume) and
    reproduces the same response statistics."""
    import yaml
    from raft_tpu.model import Model
    from raft_tpu.utils import profiling

    path = "/root/reference/examples/OC4semi-RAFT_QTF.yaml"
    if not os.path.isfile(path):
        pytest.skip("reference example not available")
    design = yaml.safe_load(open(path))
    design["settings"]["min_freq"] = 0.005
    design["settings"]["max_freq"] = 0.20
    design["platform"]["min_freq2nd"] = 0.05
    design["platform"]["df_freq2nd"] = 0.05
    design["platform"]["max_freq2nd"] = 0.25
    design["platform"]["outFolderQTF"] = str(tmp_path)

    m1 = Model(design)
    res1 = m1.analyzeCases()
    files = sorted(p.name for p in tmp_path.iterdir())
    assert "qtf-slender_body-total_Head0_Case1_WT0.12d" in files
    assert "raos-slender_body_Head0_Case1_WT0.4" in files

    # fresh model, same folder: QTF must come from the snapshot, not a
    # recompute (observed via the calcQTF_slenderBody timing registry)
    profiling.timing_report(reset=True)
    m2 = Model(design)
    res2 = m2.analyzeCases()
    times = profiling.timing_report()
    assert not any("calcQTF_slenderBody" in k for k in times), times
    np.testing.assert_allclose(
        np.asarray(res2["case_metrics"][0][0]["surge_PSD"]),
        np.asarray(res1["case_metrics"][0][0]["surge_PSD"]),
        rtol=1e-6, atol=1e-12)
    np.testing.assert_allclose(res2["mean_offsets"][0],
                               res1["mean_offsets"][0], rtol=1e-6, atol=1e-12)


def test_qtf_sharded_matches_unsharded():
    """calc_qtf_sharded over an 8-device CPU mesh == the single-device QTF
    (the context-parallel axis of SURVEY §5.7: pair-grid rows sharded,
    Hermitian completion as the only cross-device exchange)."""
    import yaml
    import jax
    from jax.sharding import Mesh

    from raft_tpu.models.fowt import build_fowt, build_seastate, fowt_pose

    path = "/root/reference/examples/OC4semi-RAFT_QTF.yaml"
    if not os.path.isfile(path):
        pytest.skip("reference example not available")
    design = yaml.safe_load(open(path))
    design["platform"]["min_freq2nd"] = 0.03
    design["platform"]["df_freq2nd"] = 0.03
    design["platform"]["max_freq2nd"] = 0.42    # 14 rows over 8 devices
    w = np.arange(0.005, 0.25, 0.005) * 2 * np.pi
    depth = float(design["site"]["water_depth"])
    fowt = build_fowt(design, w, depth=depth)
    pose = fowt_pose(fowt, np.zeros(6))
    rng = np.random.default_rng(2)
    Xi0 = (rng.standard_normal((6, len(w)))
           + 1j * rng.standard_normal((6, len(w)))) * 0.2
    M_struc = np.diag([2e7, 2e7, 2e7, 1e10, 1e10, 1e10]).astype(float)

    Q1 = np.asarray(qt.calc_qtf_slender_body(fowt, pose, 0.0, Xi0=Xi0,
                                             M_struc=M_struc))
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), axis_names=("qtf_rows",))
    Q2 = np.asarray(qt.calc_qtf_sharded(fowt, pose, 0.0, Xi0=Xi0,
                                        M_struc=M_struc, mesh=mesh))
    scale = np.abs(Q1).max()
    assert scale > 0
    np.testing.assert_allclose(Q2, Q1, atol=1e-9 * scale)
