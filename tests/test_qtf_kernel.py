"""Fused QTF pair-grid Pallas kernel: parity vs the vmapped engine.

``ops/pallas/qtf_pair.py`` re-tiles the dense (w1, w2) pair grid of
``calc_qtf_slender_body`` — w2 on the TPU lane axis, every per-pair
Pinkster/Rainey term VMEM-resident — and must change memory locality,
never numerics.  These tests run the kernel in interpret mode (its only
mode until the real/imag-split Mosaic port; see the module docstring)
against the doubly-vmapped XLA path on the same model and pin the
deviation at 1e-6, exactly like the gj_solve parity suite.
"""
import numpy as np
import pytest

from raft_tpu import _config
from raft_tpu.models.fowt import build_fowt, fowt_pose, fowt_statics
from raft_tpu.models import qtf as qt

PARITY = 1e-6


@pytest.fixture(autouse=True)
def _clear_override():
    yield
    _config.set_qtf_kernel_mode(None)


def _design(rB_z=10.0):
    """Single-spar potSecOrder design; ``rB_z`` above water gives one
    waterline-crossing member, below water gives none (the nm=0 kernel
    branch)."""
    return {
        "site": {"water_depth": 200.0, "rho_water": 1025.0, "g": 9.81},
        "platform": {
            "potModMaster": 1,
            "potSecOrder": 1,
            "min_freq2nd": 0.04, "max_freq2nd": 0.12, "df_freq2nd": 0.02,
            "members": [{
                "name": "spar", "type": 2,
                "rA": [0, 0, -20], "rB": [0, 0, rB_z],
                "shape": "circ", "gamma": 0.0, "potMod": False,
                "stations": [0, 0.5, 1], "d": [10.0, 8.0, 8.0],
                "t": 0.05, "Cd": 0.6, "Ca": 0.97,
                "CdEnd": 0.6, "CaEnd": 0.6, "rho_shell": 7850.0,
                "dlsMax": 5.0,
            }],
        },
    }


def _qtf_both_paths(design, beta=0.0, with_motion=True):
    """The full calc_qtf_slender_body through the vmapped path and the
    fused kernel on identical inputs."""
    w = np.arange(0.02, 0.25, 0.02) * 2 * np.pi
    fowt = build_fowt(design, w, depth=200.0)
    pose = fowt_pose(fowt, np.zeros(6))
    kw = {}
    if with_motion:
        stat = fowt_statics(fowt, pose)
        rng = np.random.default_rng(3)
        Xi0 = (rng.normal(size=(6, len(w)))
               + 1j * rng.normal(size=(6, len(w))))
        Xi0[3:] *= 0.01
        kw = dict(Xi0=Xi0, M_struc=np.asarray(stat["M_struc"]))
    ref = np.asarray(qt.calc_qtf_slender_body(fowt, pose, beta, **kw))
    _config.set_qtf_kernel_mode("1")
    try:
        got = np.asarray(qt.calc_qtf_slender_body(fowt, pose, beta, **kw))
    finally:
        _config.set_qtf_kernel_mode(None)
    return ref, got


def _dev(got, ref):
    return np.max(np.abs(got - ref)) / np.max(np.abs(ref))


def test_kernel_parity_waterline_member():
    """Surface-piercing spar with first-order motion: every term group
    active, including the waterline relative-elevation loop."""
    ref, got = _qtf_both_paths(_design(rB_z=10.0))
    assert got.shape == ref.shape == (5, 5, 6)
    assert _dev(got, ref) < PARITY


def test_kernel_parity_no_waterline_member():
    """Fully submerged member (nm=0): the kernel variant without the
    waterline input block."""
    ref, got = _qtf_both_paths(_design(rB_z=-5.0))
    assert _dev(got, ref) < PARITY


def test_kernel_parity_no_motion():
    """Xi0=None (diffraction-only QTF): the zero-motion degenerate the
    model uses before the first RAO is available."""
    ref, got = _qtf_both_paths(_design(rB_z=10.0), with_motion=False)
    assert _dev(got, ref) < PARITY


def test_kernel_parity_off_zero_heading():
    """beta != 0 exercises the heading-dependent wave kinematics the
    kernel receives precomputed."""
    ref, got = _qtf_both_paths(_design(rB_z=10.0), beta=0.35)
    assert _dev(got, ref) < PARITY


def test_kernel_output_hermitian():
    """The kernel feeds the same Hermitian completion as the vmapped
    path — the completed QTF must stay Hermitian per DOF."""
    _, got = _qtf_both_paths(_design(rB_z=10.0))
    for i in range(6):
        np.testing.assert_allclose(got[:, :, i], np.conj(got[:, :, i]).T,
                                   rtol=1e-12, atol=1e-10)


def test_qtf_kernel_mode_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_QTF_KERNEL", raising=False)
    assert _config.qtf_kernel_mode() == "auto"
    monkeypatch.setenv("RAFT_TPU_QTF_KERNEL", "1")
    assert _config.qtf_kernel_mode() == "1"
    monkeypatch.setenv("RAFT_TPU_QTF_KERNEL", "bogus")
    assert _config.qtf_kernel_mode() == "auto"
    _config.set_qtf_kernel_mode("0")                  # override beats env
    assert _config.qtf_kernel_mode() == "0"
    with pytest.raises(ValueError):
        _config.set_qtf_kernel_mode("2")
