"""Vectorized .12d QTF writer: byte-identical to the loop it replaced.

write_qtf_12d used to run a quadruple Python loop (O(nh*6*nw^2)
interpreted iterations); the vectorized writer must reproduce the exact
bytes — same ``% .8e`` float formatting, bare ``%d`` DOF column, and
ih-major / DOF / upper-triangle row order — and survive a round trip
through read_qtf_12d.
"""
import numpy as np
import pytest

from raft_tpu.models.qtf import read_qtf_12d, write_qtf_12d

RHO, G = 1025.0, 9.81


def _legacy_write(path, qtf, w, heads_rad, rho=RHO, g=G):
    """The pre-vectorization writer, verbatim — the byte-level oracle."""
    w = np.asarray(w)
    qtf = np.asarray(qtf)
    with open(path, "w") as f:
        ULEN = 1.0
        for ih in range(len(np.atleast_1d(heads_rad))):
            hd = np.rad2deg(np.atleast_1d(heads_rad)[ih])
            for idof in range(6):
                for i1 in range(len(w)):
                    for i2 in range(i1, len(w)):
                        F = qtf[i1, i2, ih, idof] / (rho * g * ULEN)
                        f.write(f"{2*np.pi/w[i1]: .8e} {2*np.pi/w[i2]: .8e} "
                                f"{hd: .8e} {hd: .8e} {idof+1} "
                                f"{np.abs(F): .8e} {np.angle(F): .8e} "
                                f"{F.real: .8e} {F.imag: .8e}\n")


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _random_qtf(rng, nw, nh, scale=1e6):
    q = (rng.standard_normal((nw, nw, nh, 6))
         + 1j * rng.standard_normal((nw, nw, nh, 6))) * scale
    return q


def test_writer_bytes_identical(tmp_path, rng):
    nw, nh = 7, 2
    w = np.linspace(0.2, 1.4, nw)
    heads = np.deg2rad([0.0, 30.0])
    qtf = _random_qtf(rng, nw, nh)
    qtf[2, 3, 0, 1] = 0.0           # exact zero: |F|=0, angle 0, -0 risks
    qtf[4, 4, 1, 5] = -1.25e-3      # tiny negative real
    a, b = str(tmp_path / "a.12d"), str(tmp_path / "b.12d")
    _legacy_write(a, qtf, w, heads)
    write_qtf_12d(b, qtf, w, heads)
    assert open(a, "rb").read() == open(b, "rb").read()


def test_writer_bytes_identical_single_head_scalar(tmp_path, rng):
    """heads_rad as a bare scalar (the common internal-QTF call)."""
    nw = 5
    w = np.linspace(0.3, 1.1, nw)
    qtf = _random_qtf(rng, nw, 1)
    a, b = str(tmp_path / "a.12d"), str(tmp_path / "b.12d")
    _legacy_write(a, qtf, w, 0.0)
    write_qtf_12d(b, qtf, w, 0.0)
    assert open(a, "rb").read() == open(b, "rb").read()


def test_write_read_round_trip(tmp_path, rng):
    """Hermitian QTF written then re-read reproduces the upper triangle
    (read fills the lower one by conjugate symmetry)."""
    nw = 6
    w = np.linspace(0.25, 1.25, nw)
    q = _random_qtf(rng, nw, 1)
    i_low = np.tril_indices(nw, -1)
    q[i_low[0], i_low[1], :, :] = np.conj(q[i_low[1], i_low[0], :, :])
    path = str(tmp_path / "rt.12d")
    write_qtf_12d(path, q, w, 0.0)
    back = read_qtf_12d(path, rho=RHO, g=G)
    np.testing.assert_allclose(back.w, w, rtol=1e-7)
    np.testing.assert_allclose(back.qtf[..., 0, :], q[..., 0, :],
                               rtol=1e-6, atol=1e-6 * np.abs(q).max())
