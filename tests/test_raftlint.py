"""raftlint (tools/raftlint): per-rule fixtures, suppressions, baseline,
config, CLI — and the self-clean gate that keeps raft_tpu/ lint-clean.

Every rule is proven BOTH ways: it fires on a violating fixture and
stays silent on the sanctioned pattern (obs/transfers.py exit points,
recovery.py seams, ``# print-ok``).  The RTL001 canary seeds an
unsanctioned ``jax.device_get`` into a jitted function — the static
twin of the PR 4 transfer-budget runtime test.
"""
import json
import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.raftlint import (Config, baseline_doc, lint, load_config,  # noqa: E402
                            main as raftlint_main)
from tools.raftlint.config import _parse_toml_minimal  # noqa: E402


def lint_src(tmp_path, src, select, relname="raft_tpu/ops/mod.py",
             options=None, baseline_path=None):
    """Lint one dedented fixture at a repo-shaped relative path."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    cfg = Config(root=str(tmp_path))
    if options:
        cfg.rule_options.update(options)
    return lint(paths=[relname], root=str(tmp_path), config=cfg,
                select={select} if isinstance(select, str) else select,
                baseline_path=baseline_path)


# ---------------------------------------------------------------------------
# RTL001 — host-transfer escape
# ---------------------------------------------------------------------------

CANARY_DEVICE_GET = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def solve(Z, F):
        X = jnp.linalg.solve(Z, F)
        bad = jax.device_get(X)          # unsanctioned pull inside jit
        return bad
"""


def test_rtl001_canary_unsanctioned_device_get(tmp_path):
    rep = lint_src(tmp_path, CANARY_DEVICE_GET, "RTL001",
                   relname="raft_tpu/model.py")
    assert len(rep.findings) == 1
    assert "device_get" in rep.findings[0].message
    assert rep.findings[0].rule == "RTL001"


def test_rtl001_sanctioned_transfers_module_is_exempt(tmp_path):
    rep = lint_src(tmp_path, CANARY_DEVICE_GET, "RTL001",
                   relname="raft_tpu/obs/transfers.py")
    assert rep.findings == []


def test_rtl001_np_asarray_in_partial_jit(tmp_path):
    rep = lint_src(tmp_path, """
        from functools import partial
        import jax
        import numpy as np

        @partial(jax.jit, donate_argnums=(0,))
        def f(x):
            return np.asarray(x) + 1
    """, "RTL001")
    assert len(rep.findings) == 1
    assert "np.asarray" in rep.findings[0].message


def test_rtl001_float_cast_in_lax_body_fires(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        def body(carry):
            return carry + float(carry)

        def run(x0):
            return jax.lax.while_loop(lambda c: c < 3, body, x0)
    """, "RTL001")
    assert len(rep.findings) == 1
    assert "float()" in rep.findings[0].message


def test_rtl001_static_param_cast_is_silent(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        @jax.jit(static_argnames=("n",))
        def f(x, n):
            return x * int(n)
    """, "RTL001")
    assert rep.findings == []


def test_rtl001_item_and_block_until_ready_in_jit(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        def g(x):
            return x.sum().item() + 1

        gj = jax.jit(g)

        def host(x):
            # host orchestration: not device scope, no finding
            return x.block_until_ready()
    """, "RTL001")
    assert len(rep.findings) == 1
    assert ".item()" in rep.findings[0].message


def test_rtl001_raw_device_get_outside_jit_fires(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        def pull(x):
            return jax.device_get(x)
    """, "RTL001")
    assert len(rep.findings) == 1
    assert "obs.transfers.device_get" in rep.findings[0].message


def test_rtl001_inline_suppression(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        def pull(x):
            return jax.device_get(x)  # raftlint: disable=RTL001 bootstrap
    """, "RTL001")
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_rtl001_builtin_map_is_not_a_jax_transform(tmp_path):
    """Host-only code using builtin map()/local helpers named like lax
    transforms must not be marked device scope."""
    rep = lint_src(tmp_path, """
        def parse(row):
            return float(row)

        def cond(flag):
            return bool(flag)

        def load(rows):
            return list(map(parse, rows)) + [cond(True)]
    """, {"RTL001", "RTL002"})
    assert rep.findings == []


def test_rtl001_static_shape_casts_are_exempt(tmp_path):
    rep = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, xs):
            n = int(x.shape[0])
            m = float(len(xs)) + x.ndim
            return jnp.sum(x) / n + m
    """, "RTL001")
    assert rep.findings == []


# the probe-channel contract: host callbacks may appear ONLY in
# obs/probes.py (its traffic is counted in raft_tpu_probe_events_total)
HOST_CALLBACK = """
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    @jax.jit
    def solve(x):
        jax.debug.callback(lambda v: None, jnp.max(x))
        return x + 1

    def stream(x):
        return io_callback(lambda v: v, x, x)
"""


def test_rtl001_host_callback_fires_outside_probes(tmp_path):
    rep = lint_src(tmp_path, HOST_CALLBACK, "RTL001",
                   relname="raft_tpu/model.py")
    assert len(rep.findings) == 2
    assert all("obs.probes" in f.message for f in rep.findings)
    assert any("debug" in f.message for f in rep.findings)
    assert any("io_callback" in f.message for f in rep.findings)


def test_rtl001_probe_module_is_sanctioned(tmp_path):
    rep = lint_src(tmp_path, HOST_CALLBACK, "RTL001",
                   relname="raft_tpu/obs/probes.py")
    assert rep.findings == []


# ---------------------------------------------------------------------------
# RTL002 — recompile hazard
# ---------------------------------------------------------------------------

def test_rtl002_python_branch_on_traced_param(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """, "RTL002")
    assert len(rep.findings) == 1
    assert "if" in rep.findings[0].message


def test_rtl002_none_check_and_static_param_are_silent(tmp_path):
    rep = lint_src(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, xf=None, mode="fast"):
            if xf is None:
                xf = x
            if mode == "fast":
                return x + xf
            return x - xf
    """, "RTL002")
    assert rep.findings == []


def test_rtl002_while_on_traced_param_in_scanned_fn(tmp_path):
    rep = lint_src(tmp_path, """
        from jax import lax

        def body(carry, item):
            while carry > 0:
                carry = carry - item
            return carry, item

        def run(x0, xs):
            return lax.scan(body, x0, xs)
    """, "RTL002")
    assert len(rep.findings) == 1
    assert "while" in rep.findings[0].message


def test_rtl002_jit_in_loop(tmp_path):
    rep = lint_src(tmp_path, """
        import jax

        def resolve(solvers, xs):
            out = []
            for s in solvers:
                out.append(jax.jit(s.batched)(xs))
            return out

        top = jax.jit(resolve)  # not in a loop: silent
    """, "RTL002")
    assert len(rep.findings) == 1
    assert "inside a Python loop" in rep.findings[0].message


def test_rtl002_static_argnames_typo_and_unhashable_default(tmp_path):
    rep = lint_src(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, modes=[1, 2]):
            return x

        @partial(jax.jit, static_argnums=(1,))
        def g(x, opts={}):
            return x
    """, "RTL002")
    msgs = " | ".join(f.message for f in rep.findings)
    assert "does not name a parameter" in msgs
    assert "unhashable" in msgs


# ---------------------------------------------------------------------------
# RTL003 — dtype discipline
# ---------------------------------------------------------------------------

def test_rtl003_dtypeless_ctors_fire_in_device_modules(tmp_path):
    rep = lint_src(tmp_path, """
        import jax.numpy as jnp

        def build(n):
            a = jnp.zeros((n, n))
            b = jnp.arange(n)
            c = jnp.linspace(0.0, 1.0, n)
            ok1 = jnp.zeros((n,), jnp.int32)
            ok2 = jnp.ones((n,), dtype=float)
            ok3 = jnp.arange(n, dtype=jnp.int32)
            ok4 = jnp.zeros_like(a)
            return a, b, c, ok1, ok2, ok3, ok4
    """, "RTL003")
    assert len(rep.findings) == 3
    assert {f.line_text.strip().split(" = ")[0]
            for f in rep.findings} == {"a", "b", "c"}


def test_rtl003_silent_outside_device_modules(tmp_path):
    rep = lint_src(tmp_path, """
        import jax.numpy as jnp
        x = jnp.zeros((3, 3))
    """, "RTL003", relname="raft_tpu/models/fixture.py")
    assert rep.findings == []


def test_rtl003_numpy_dtype_literal(tmp_path):
    rep = lint_src(tmp_path, """
        import numpy as np

        def cast(x):
            return x.astype(np.float64)
    """, "RTL003", relname="raft_tpu/parallel/fixture.py")
    assert len(rep.findings) == 1
    assert "np.float64" in rep.findings[0].message


# ---------------------------------------------------------------------------
# RTL004 — exception discipline
# ---------------------------------------------------------------------------

def test_rtl004_builtin_raise_fires_taxonomy_silent(tmp_path):
    rep = lint_src(tmp_path, """
        from raft_tpu import errors
        from raft_tpu.errors import ModelConfigError

        def solve(bad):
            if bad == 1:
                raise ValueError("untyped")             # finding
            if bad == 2:
                raise errors.DynamicsSingular("typed")   # ok
            if bad == 3:
                raise ModelConfigError("typed")          # ok
            if bad == 4:
                raise FileNotFoundError("missing.yaml")  # allowed builtin
            raise NotImplementedError("abstract")        # allowed builtin
    """, "RTL004")
    assert len(rep.findings) == 1
    assert "raise ValueError" in rep.findings[0].message


def test_rtl004_broad_except_fires_outside_seams(tmp_path):
    src = """
        def f():
            try:
                return 1
            except Exception:
                return 2

        def g():
            try:
                return 1
            except:
                return 2

        def ok():
            try:
                return 1
            except (ValueError, OSError):
                return 2
    """
    rep = lint_src(tmp_path, src, "RTL004",
                   relname="raft_tpu/parallel/fixture.py")
    assert len(rep.findings) == 2
    # identical file inside the sanctioned seam: silent
    rep2 = lint_src(tmp_path, src, "RTL004",
                    relname="raft_tpu/recovery.py")
    assert rep2.findings == []


def test_rtl004_raise_scope_excludes_models(tmp_path):
    rep = lint_src(tmp_path, """
        def parse(x):
            raise ValueError("models/ raise scope is config validation")
    """, "RTL004", relname="raft_tpu/models/fixture.py")
    assert rep.findings == []


#: the repo's configured RTL004 options (pyproject.toml) — the serve
#: layer is a solve-path module whose two keep-alive seams (the request
#: worker and the watchdog callback dispatch) are config-sanctioned for
#: broad except
_RTL004_SERVE_OPTS = {"rtl004": {
    "solve-modules": ["raft_tpu/model.py", "raft_tpu/ops",
                      "raft_tpu/parallel", "raft_tpu/io",
                      "raft_tpu/recovery.py", "raft_tpu/serve"],
    "except-sanctioned": ["raft_tpu/recovery.py",
                          "raft_tpu/testing/faults.py", "raft_tpu/obs",
                          "raft_tpu/serve/service.py",
                          "raft_tpu/serve/watchdog.py",
                          "raft_tpu/serve/journal.py",
                          "raft_tpu/serve/replica.py",
                          "raft_tpu/serve/router.py",
                          "raft_tpu/serve/resultstore.py"],
}}

_SERVE_SEAM_SRC = """
    def worker_loop(batches):
        for b in batches:
            try:
                b.run()
            except Exception:      # keep-alive seam
                b.fail_typed()

    def submit(bad):
        if bad:
            raise ValueError("untyped admission failure")
"""


def test_rtl004_serve_seams_sanctioned_pair(tmp_path):
    """The serve fixture fires OUTSIDE the two sanctioned seam files
    (both the broad except and the untyped raise, since serve/ is a
    solve-path module) and stays silent INSIDE them for the broad
    except."""
    rep = lint_src(tmp_path, _SERVE_SEAM_SRC, "RTL004",
                   relname="raft_tpu/serve/handlers.py",
                   options=_RTL004_SERVE_OPTS)
    msgs = [f.message for f in rep.findings]
    assert len(msgs) == 2
    assert any("except" in m for m in msgs)
    assert any("raise ValueError" in m for m in msgs)
    # identical file at the sanctioned worker seam: the broad except is
    # silent; the raise discipline still applies (sanctioning is for
    # excepts only — typed raises are required everywhere in serve/)
    rep2 = lint_src(tmp_path, _SERVE_SEAM_SRC, "RTL004",
                    relname="raft_tpu/serve/service.py",
                    options=_RTL004_SERVE_OPTS)
    assert len(rep2.findings) == 1
    assert "raise ValueError" in rep2.findings[0].message
    rep3 = lint_src(tmp_path, """
        def tick(cb):
            try:
                cb()
            except Exception:
                pass
    """, "RTL004", relname="raft_tpu/serve/watchdog.py",
                    options=_RTL004_SERVE_OPTS)
    assert rep3.findings == []


_DURABILITY_SRC = """
    from raft_tpu import errors

    def scan(journal_dir, strict):
        if strict:
            raise errors.JournalCorrupt("torn records")     # typed: ok
        raise RuntimeError("untyped corruption")            # finding

    def write(rec, sink, count):
        try:
            sink.write(rec)
        except Exception:           # WAL keep-alive seam
            count()
"""


def test_rtl004_durability_modules_fixture_pair(tmp_path):
    """serve/journal.py and serve/tenancy.py are solve-path modules:
    the untyped raise fires in BOTH (journal corruption must be the
    typed JournalCorrupt, tenancy misconfig ModelConfigError); the
    WAL write seam's broad except is config-sanctioned in journal.py
    only — in tenancy (or any other serve file) it fires."""
    rep = lint_src(tmp_path, _DURABILITY_SRC, "RTL004",
                   relname="raft_tpu/serve/tenancy.py",
                   options=_RTL004_SERVE_OPTS)
    msgs = [f.message for f in rep.findings]
    assert len(msgs) == 2
    assert any("raise RuntimeError" in m for m in msgs)
    assert any("except" in m for m in msgs)
    # identical file at the sanctioned journal seam: the broad except
    # is silent, the raise discipline still fires
    rep2 = lint_src(tmp_path, _DURABILITY_SRC, "RTL004",
                    relname="raft_tpu/serve/journal.py",
                    options=_RTL004_SERVE_OPTS)
    assert len(rep2.findings) == 1
    assert "raise RuntimeError" in rep2.findings[0].message


_REPLICATION_SRC = """
    from raft_tpu import errors

    def health_sweep(backends):
        for b in backends:
            try:
                b.probe()
            except Exception:       # keep-alive seam
                b.healthy = False

    def ship(rec, peer):
        if peer.gone:
            raise RuntimeError("untyped replication failure")
"""


def test_rtl004_replication_modules_fixture_pair(tmp_path):
    """serve/replica.py and serve/router.py are solve-path modules with
    config-sanctioned keep-alive seams: the broad except (a peer store
    / backend failing must never take the mirror or router down) is
    silent INSIDE them and fires in any other serve file; the untyped
    raise fires everywhere (replication trouble must be the typed
    ReplicaLagExceeded / AdmissionRejected)."""
    for seam in ("raft_tpu/serve/replica.py",
                 "raft_tpu/serve/router.py"):
        rep = lint_src(tmp_path, _REPLICATION_SRC, "RTL004",
                       relname=seam, options=_RTL004_SERVE_OPTS)
        assert len(rep.findings) == 1, seam
        assert "raise RuntimeError" in rep.findings[0].message
    # identical file anywhere else in serve/: BOTH fire
    rep2 = lint_src(tmp_path, _REPLICATION_SRC, "RTL004",
                    relname="raft_tpu/serve/mirroring.py",
                    options=_RTL004_SERVE_OPTS)
    msgs = [f.message for f in rep2.findings]
    assert len(msgs) == 2
    assert any("except" in m for m in msgs)
    assert any("raise RuntimeError" in m for m in msgs)


_RESULTSTORE_SRC = """
    from raft_tpu import errors

    def put_entry(path, data):
        try:
            with open(path, "wb") as f:
                f.write(data)
        except Exception:        # counted put gap, never a dead service
            return False
        return True

    def verify(doc):
        if doc is None:
            raise RuntimeError("untyped store corruption")
"""


def test_rtl004_resultstore_fixture_pair(tmp_path):
    """serve/resultstore.py is a solve-path module with a
    config-sanctioned keep-alive seam: a store put/read failing must be
    a counted gap or a delete-and-miss, never a dead service — so its
    broad except is silent INSIDE resultstore.py and fires in any
    other serve file; the untyped raise fires everywhere (store
    corruption must be the typed ResultStoreCorrupt)."""
    rep = lint_src(tmp_path, _RESULTSTORE_SRC, "RTL004",
                   relname="raft_tpu/serve/resultstore.py",
                   options=_RTL004_SERVE_OPTS)
    assert len(rep.findings) == 1
    assert "raise RuntimeError" in rep.findings[0].message
    # identical file anywhere else in serve/: BOTH fire
    rep2 = lint_src(tmp_path, _RESULTSTORE_SRC, "RTL004",
                    relname="raft_tpu/serve/readtier.py",
                    options=_RTL004_SERVE_OPTS)
    msgs = [f.message for f in rep2.findings]
    assert len(msgs) == 2
    assert any("except" in m for m in msgs)
    assert any("raise RuntimeError" in m for m in msgs)


# ---------------------------------------------------------------------------
# RTL005 — logging discipline
# ---------------------------------------------------------------------------

def test_rtl005_bare_print_and_exemptions(tmp_path):
    rep = lint_src(tmp_path, """
        def report(x):
            print(x)                       # finding
            print_timing_report(x)         # not the builtin
            x.print()                      # method, not the builtin

        def table(x):
            print("| col |")  # print-ok: deliberate report printer
    """, "RTL005", relname="raft_tpu/utils/fixture.py")
    assert len(rep.findings) == 1
    assert rep.findings[0].line_text.strip().startswith("print(x)")
    assert len(rep.suppressed) == 1


def test_rtl005_plot_py_exempt(tmp_path):
    rep = lint_src(tmp_path, "print('interactive')\n", "RTL005",
                   relname="raft_tpu/plot.py")
    assert rep.findings == []


# ---------------------------------------------------------------------------
# RTL006 — sharding locality
# ---------------------------------------------------------------------------

STRAY_SHARDING = """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    def solve_batched(st, Xi, mesh):
        # stray resharding outside the partition layer
        Xi = jax.lax.with_sharding_constraint(
            Xi, NamedSharding(mesh, P("cases", None, "freq")))
        return Xi

    def build(devices):
        return Mesh(devices, axis_names=("variants", "cases"))
"""


def test_rtl006_fires_outside_partition_layer(tmp_path):
    rep = lint_src(tmp_path, STRAY_SHARDING, "RTL006",
                   relname="raft_tpu/parallel/sweep.py")
    msgs = [f.message for f in rep.findings]
    # the constraint call, the axis literals in NamedSharding/P, and
    # the Mesh axis_names literal all fire
    assert any("with_sharding_constraint" in m for m in msgs)
    assert any("'cases'" in m and "PartitionSpec" in m for m in msgs)
    assert any("Mesh" in m for m in msgs)
    assert all(f.rule == "RTL006" for f in rep.findings)


def test_rtl006_partition_layer_is_sanctioned(tmp_path):
    rep = lint_src(tmp_path, STRAY_SHARDING, "RTL006",
                   relname="raft_tpu/parallel/partition.py")
    assert rep.findings == []


def test_rtl006_plain_strings_and_other_calls_silent(tmp_path):
    """Axis-name words in ordinary strings/calls are not sharding
    constructors; axis-free sharding ctors carry no literal to flag."""
    rep = lint_src(tmp_path, """
        from jax.sharding import Mesh, PartitionSpec as P

        def describe(log):
            log.info("sweep over cases and freq bins")   # free text
            record(kind="cases")                         # not a ctor
            return P()                                   # no axis name

        def build(devices, axes):
            return Mesh(devices, axis_names=axes)        # no literal
    """, "RTL006", relname="raft_tpu/parallel/sweep.py")
    assert rep.findings == []


# ---------------------------------------------------------------------------
# RTL007 — persistence write-path discipline
# ---------------------------------------------------------------------------

RAW_PERSIST_WRITE = """
    import json, os

    def put(path, doc):
        with open(path + ".tmp", "w") as f:    # raw write path
            json.dump(doc, f)
        os.replace(path + ".tmp", path)
"""


def test_rtl007_fires_on_raw_write_in_persistence_module(tmp_path):
    rep = lint_src(tmp_path, RAW_PERSIST_WRITE, "RTL007",
                   relname="raft_tpu/serve/checkpoint.py")
    assert len(rep.findings) == 1
    assert "fsync_write" in rep.findings[0].message
    assert rep.findings[0].rule == "RTL007"


def test_rtl007_shared_helper_reads_and_sanction_silent(tmp_path):
    """The shared helper itself is the sanctioned write shape,
    read-mode opens are out of scope, and a config-sanctioned file
    keeps its raw writes."""
    rep = lint_src(tmp_path, """
        import os, threading

        def fsync_write(path, data):
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:         # THE helper: sanctioned
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

        def read(path):
            with open(path, "rb") as f:        # read-mode: fine
                return f.read()
    """, "RTL007", relname="raft_tpu/serve/checkpoint.py")
    assert rep.findings == []
    # identical raw write in a config-sanctioned file: silent
    rep = lint_src(
        tmp_path, RAW_PERSIST_WRITE, "RTL007",
        relname="raft_tpu/serve/checkpoint.py",
        options={"rtl007": {
            "sanctioned": ["raft_tpu/serve/checkpoint.py"]}})
    assert rep.findings == []
    # a module outside the persistence list is out of scope
    rep = lint_src(tmp_path, RAW_PERSIST_WRITE, "RTL007",
                   relname="raft_tpu/utils/fixture.py")
    assert rep.findings == []


#: the repo's configured coverage of the learned read tier
#: (pyproject.toml): models/surrogate_net.py is the ONE models/ file
#: on the serving path (RTL004 typed-raise discipline), and
#: serve/surrogate.py publishes durable bundles/pointers/markers
#: (RTL007 fsync-helper discipline)
_SURROGATE_OPTS = {"rtl004": {
    "solve-modules": ["raft_tpu/model.py", "raft_tpu/ops",
                      "raft_tpu/parallel", "raft_tpu/io",
                      "raft_tpu/recovery.py", "raft_tpu/serve",
                      "raft_tpu/models/surrogate_net.py"],
},
    "rtl007": {"persistence-modules": [
        "raft_tpu/serve/checkpoint.py",
        "raft_tpu/serve/resultstore.py",
        "raft_tpu/serve/journal.py",
        "raft_tpu/serve/surrogate.py"]}}

_SURROGATE_NET_SRC = """
    from raft_tpu import errors

    def fit(X, Y):
        if X.shape[0] < 2:
            raise errors.ModelConfigError("corpus too small")  # typed
        if X.shape[1] != 3:
            raise ValueError("untyped feature-width failure")
"""


def test_rtl004_covers_surrogate_net_fixture_pair(tmp_path):
    """models/ raises are normally out of RTL004 scope (config
    validation lives there), but surrogate_net.py serves predictions
    on the admission path — the typed taxonomy applies to it alone."""
    rep = lint_src(tmp_path, _SURROGATE_NET_SRC, "RTL004",
                   relname="raft_tpu/models/surrogate_net.py",
                   options=_SURROGATE_OPTS)
    assert len(rep.findings) == 1
    assert "raise ValueError" in rep.findings[0].message
    # the identical file anywhere else in models/ keeps the relaxed
    # scope — listing ONE file must not drag the whole package in
    rep2 = lint_src(tmp_path, _SURROGATE_NET_SRC, "RTL004",
                    relname="raft_tpu/models/fixture.py",
                    options=_SURROGATE_OPTS)
    assert rep2.findings == []


def test_rtl007_covers_surrogate_bundle_writes_fixture_pair(tmp_path):
    """Bundle/pointer/quarantine-marker publishes in serve/surrogate.py
    are durable serving state: a raw write fires; routing through the
    shared fsync helper (the module's actual shape) is silent."""
    rep = lint_src(tmp_path, RAW_PERSIST_WRITE, "RTL007",
                   relname="raft_tpu/serve/surrogate.py",
                   options=_SURROGATE_OPTS)
    assert len(rep.findings) == 1
    assert "fsync_write" in rep.findings[0].message
    rep2 = lint_src(tmp_path, """
        import json
        from raft_tpu.obs.journalio import fsync_write

        def _fsync_write(path, data):
            fsync_write(path, data)

        def publish(pointer, doc):
            _fsync_write(pointer, json.dumps(doc).encode())

        def load(path):
            with open(path, "rb") as f:        # read-mode: fine
                return f.read()
    """, "RTL007", relname="raft_tpu/serve/surrogate.py",
                    options=_SURROGATE_OPTS)
    assert rep2.findings == []


# ---------------------------------------------------------------------------
# suppressions / baseline / config / CLI
# ---------------------------------------------------------------------------

def test_malformed_suppression_never_widens(tmp_path):
    """A typo'd directive must REPORT the finding, not silently become
    a blanket all-rules suppression."""
    for bad in ("# raftlint: disabled=RTL003",      # typo'd verb
                "# raftlint: disable RTL003",       # missing '='
                "# raftlint: disable="):            # '=' with no codes
        rep = lint_src(tmp_path, f"""
            import jax.numpy as jnp
            x = jnp.zeros((3, 3))  {bad}
        """, "RTL003")
        assert len(rep.findings) == 1, bad
        assert rep.suppressed == [], bad
    # the legitimate forms still work
    for ok in ("# raftlint: disable=RTL003 legacy shim",
               "# raftlint: disable — grandfathered"):
        rep = lint_src(tmp_path, f"""
            import jax.numpy as jnp
            x = jnp.zeros((3, 3))  {ok}
        """, "RTL003")
        assert rep.findings == [] and len(rep.suppressed) == 1, ok


def test_malformed_baseline_is_invocation_error(tmp_path, capsys):
    path = tmp_path / "raft_tpu" / "ops" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("import jax.numpy as jnp\nx = jnp.zeros(3)\n")
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"schema": "raftlint.baseline/v1",
                              "findings": [{"path": "x.py"}]}))
    rc = raftlint_main(["--root", str(tmp_path), "--baseline", str(bl),
                        "raft_tpu"])
    err = capsys.readouterr().err
    assert rc == 2 and "baseline finding #0" in err


def test_obsctl_lint_output_lands_in_invoker_cwd(tmp_path):
    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsctl.py"),
         "lint", "--format", "json", "--output", "report.json",
         "raft_tpu"],
        cwd=tmp_path, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert (tmp_path / "report.json").is_file()
    assert json.loads((tmp_path / "report.json").read_text())["ok"]


def test_blanket_suppression_covers_all_rules(tmp_path):
    rep = lint_src(tmp_path, """
        import jax.numpy as jnp
        x = jnp.zeros((3, 3))  # raftlint: disable
    """, {"RTL003", "RTL005"})
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_baseline_grandfathers_existing_findings(tmp_path):
    src = """
        import jax.numpy as jnp
        a = jnp.zeros((3, 3))
    """
    rep = lint_src(tmp_path, src, "RTL003")
    assert len(rep.findings) == 1
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(baseline_doc(rep.findings)))
    rep2 = lint_src(tmp_path, src, "RTL003", baseline_path=str(bl))
    assert rep2.ok and len(rep2.baselined) == 1
    # a NEW duplicate of the same pattern is NOT covered by the
    # one-entry baseline (counts are per-fingerprint)
    rep3 = lint_src(tmp_path, src + "    b = jnp.zeros((3, 3))\n",
                    "RTL003", baseline_path=str(bl))
    assert len(rep3.findings) == 1 and len(rep3.baselined) == 1
    # baseline matching survives line-number drift
    rep4 = lint_src(tmp_path, "\n\n# moved\n" + textwrap.dedent(src),
                    "RTL003", baseline_path=str(bl))
    assert rep4.ok and len(rep4.baselined) == 1


def test_pyproject_config_disable_and_options(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.raftlint]
        disable = ["RTL003"]

        [tool.raftlint.rtl005]
        exempt-files = ["fixture.py"]
    """))
    cfg = load_config(str(tmp_path))
    assert not cfg.enabled("RTL003") and cfg.enabled("RTL004")
    assert cfg.options("RTL005")["exempt-files"] == ["fixture.py"]
    path = tmp_path / "raft_tpu" / "ops" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("import jax.numpy as jnp\nx = jnp.zeros(3)\n"
                    "print('hi')\n")
    rep = lint(paths=["raft_tpu"], root=str(tmp_path), config=cfg)
    assert rep.findings == []        # RTL003 disabled, RTL005 exempt


def test_minimal_toml_parser_matches_schema(tmp_path):
    doc = _parse_toml_minimal(textwrap.dedent("""
        # comment
        [tool.raftlint]
        paths = ["raft_tpu"]        # trailing comment
        baseline = "tools/raftlint/baseline.json"
        disable = []

        [tool.raftlint.rtl004]
        raise-allowed = [
          "FileNotFoundError",
          "NotImplementedError",
        ]
        flag = true
        n = 3
    """))
    rl = doc["tool"]["raftlint"]
    assert rl["paths"] == ["raft_tpu"]
    assert rl["baseline"] == "tools/raftlint/baseline.json"
    assert rl["disable"] == []
    assert rl["rtl004"]["raise-allowed"] == ["FileNotFoundError",
                                             "NotImplementedError"]
    assert rl["rtl004"]["flag"] is True and rl["rtl004"]["n"] == 3


def test_minimal_toml_parser_tolerates_foreign_tables():
    """Multi-line arrays with inline tables or bracket-bearing strings
    in FOREIGN pyproject tables must neither crash the 3.10 fallback
    parser nor swallow the [tool.raftlint] section behind them."""
    doc = _parse_toml_minimal(textwrap.dedent("""
        [tool.cibuildwheel]
        environment = [
          { FOO = "bar" },
        ]
        matrix = [
          "contains [ bracket",
          "and ] another",
        ]

        [tool.raftlint]
        paths = ["raft_tpu"]
    """))
    assert doc["tool"]["raftlint"]["paths"] == ["raft_tpu"]


def test_overlapping_paths_lint_each_file_once(tmp_path):
    path = tmp_path / "raft_tpu" / "ops" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("import jax.numpy as jnp\nx = jnp.zeros(3)\n")
    rep = lint(paths=["raft_tpu", "raft_tpu/ops/fixture.py"],
               root=str(tmp_path), config=Config(root=str(tmp_path)),
               select={"RTL003"})
    assert len(rep.findings) == 1 and rep.checked_files == 1


def test_repo_pyproject_parses_identically_with_fallback():
    """The committed [tool.raftlint] tables must read the same through
    tomllib and through the 3.10 fallback parser."""
    with open(os.path.join(REPO, "pyproject.toml"), encoding="utf-8") as f:
        text = f.read()
    fallback = _parse_toml_minimal(text)["tool"]["raftlint"]
    try:
        import tomllib
    except ImportError:
        pytest.skip("no tomllib to compare against (py3.10)")
    reference = tomllib.loads(text)["tool"]["raftlint"]
    assert fallback == reference


def test_cli_exit_codes_and_json(tmp_path, capsys):
    path = tmp_path / "raft_tpu" / "ops" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("import jax.numpy as jnp\nx = jnp.zeros(3)\n")
    rc = raftlint_main(["--root", str(tmp_path), "--format", "json",
                        "raft_tpu"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and not out["ok"]
    assert out["findings"][0]["rule"] == "RTL003"
    rc = raftlint_main(["--root", str(tmp_path), "--select", "RTL005",
                        "raft_tpu"])
    capsys.readouterr()
    assert rc == 0
    assert raftlint_main(["--list-rules"]) == 0
    rules_out = capsys.readouterr().out
    for code in ("RTL001", "RTL002", "RTL003", "RTL004", "RTL005"):
        assert code in rules_out


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    path = tmp_path / "raft_tpu" / "ops" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("import jax.numpy as jnp\nx = jnp.zeros(3)\n")
    bl = str(tmp_path / "bl.json")
    assert raftlint_main(["--root", str(tmp_path), "--baseline", bl,
                          "--write-baseline", "raft_tpu"]) == 0
    capsys.readouterr()
    assert raftlint_main(["--root", str(tmp_path), "--baseline", bl,
                          "raft_tpu"]) == 0


def test_parse_error_is_reported_not_crash(tmp_path, capsys):
    path = tmp_path / "raft_tpu" / "broken.py"
    path.parent.mkdir(parents=True)
    path.write_text("def f(:\n")
    rep = lint(paths=["raft_tpu"], root=str(tmp_path),
               config=Config(root=str(tmp_path)))
    assert not rep.ok
    assert rep.parse_errors and rep.parse_errors[0].rule == "RTL000"
    # CLI contract: broken INPUT is exit 2 (bad input), not exit 1
    # (contract findings)
    rc = raftlint_main(["--root", str(tmp_path), "raft_tpu"])
    capsys.readouterr()
    assert rc == 2


# ---------------------------------------------------------------------------
# the self-clean gate: raft_tpu/ lints at ZERO unsuppressed findings
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    cfg = load_config(REPO)
    rep = lint(root=REPO, config=cfg)
    assert rep.ok, (
        "raftlint found unsuppressed findings in raft_tpu/ — fix them, "
        "suppress with a justified `# raftlint: disable=RTL0xx`, or (last "
        "resort) baseline them:\n" + "\n".join(
            f"{f.path}:{f.line}: {f.rule} {f.message}"
            for f in rep.all_reported()))
    assert rep.checked_files > 40     # the whole package was walked
