"""Fault-tolerant case execution: taxonomy, ladder, quarantine, resume.

Cheap unit tests cover the fault-spec grammar, the typed-error
taxonomy's back-compat contracts, the ladder engine, the executable
cache's corrupt-entry delete-and-miss, and the journal round trip.

The module-scoped ``cyl_runs`` fixture drives the full machinery through
one coarse Vertical_cylinder model (the cheapest vendored design):

- clean 3-case run (the parity baseline),
- fault-injected run (``nan@dynamics:case=1`` persistent -> ladder
  exhausted -> case 1 quarantined, cases 0/2 complete),
- ``resume=True`` run against the faulted run's journal (cases 0/2
  restored without re-solving, case 1 re-run clean),
- ``raise@kernel:case=0:once`` single-case run (ladder fires
  configured -> jnp_solve and recovers at exact parity).

The ISSUE acceptance scenario on the 3-case OC3 spar runs the same
assertions end-to-end in the slow tier
(``test_oc3_three_case_acceptance``).
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import _config, errors, obs, recovery
from raft_tpu.io.designs import load_design
from raft_tpu.model import Model
from raft_tpu.testing import faults

NW_SETTINGS = {"min_freq": 0.05, "max_freq": 0.5}


def _cyl_design(ncases=3):
    design = load_design("Vertical_cylinder")
    design.setdefault("settings", {})
    design["settings"].update(NW_SETTINGS)
    row0 = list(design["cases"]["data"][0])
    ih = design["cases"]["keys"].index("wave_height")
    rows = []
    for i in range(ncases):
        row = list(row0)
        row[ih] = 1.0 + 0.5 * i
        rows.append(row)
    design["cases"]["data"] = rows
    return design


def _digests(ledger):
    return {e["key"]: e["digest"] for e in ledger["entries"]}


def _entry(ledger, key):
    return next(e for e in ledger["entries"] if e["key"] == key)


# ---------------------------------------------------------------------------
# unit: fault-spec grammar
# ---------------------------------------------------------------------------

def test_fault_spec_parsing():
    specs = faults.parse(
        "nan@dynamics:case=2,raise@statics:case=0:once,"
        "corrupt@exec_cache,raise@kernel:times=3,bogus@nowhere,garbage")
    assert [f["action"] for f in specs] == ["nan", "raise", "corrupt",
                                           "raise"]
    assert specs[0]["match"] == {"case": 2} and specs[0]["times"] is None
    assert specs[1]["times"] == 1
    assert specs[3]["times"] == 3
    # malformed qualifiers and unsupported action/site combinations are
    # dropped, never raised — injection must not take down a run
    assert faults.parse("nan@dynamics:times=2x") == []
    assert faults.parse("raise@exec_cache") == []
    assert faults.parse("nan@kernel") == []


def test_fault_fire_matching_and_exhaustion():
    faults.install("raise@statics:case=0:once,nan@dynamics:case=2")
    try:
        assert faults.fire("statics", case=1) is None
        assert faults.fire("dynamics", case=2) == "nan"
        assert faults.fire("dynamics", case=2) == "nan"   # unlimited
        with pytest.raises(errors.StaticsDivergence) as exc:
            faults.maybe_raise("statics", case=0)
        assert exc.value.injected
        assert faults.fire("statics", case=0) is None     # once: spent
        # ambient context reaches sites that can't pass kwargs
        faults.install("raise@kernel:case=5")
        with faults.context(case=5):
            assert faults.fire("kernel") == "raise"
        assert faults.fire("kernel") is None
    finally:
        faults.clear()


def test_corrupt_bytes_deterministic():
    faults.install("corrupt@exec_cache")
    try:
        data = b"x" * 64
        c1 = faults.corrupt_bytes("exec_cache", data)
        faults.install("corrupt@exec_cache")
        c2 = faults.corrupt_bytes("exec_cache", data)
        assert c1 == c2 != data
        faults.clear()
        assert faults.corrupt_bytes("exec_cache", data) == data
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# unit: taxonomy back-compat and structured context
# ---------------------------------------------------------------------------

def test_error_taxonomy_compat():
    e = errors.NonFiniteResult("bad", case=3, n_bad=7)
    assert isinstance(e, FloatingPointError)    # old solveDynamics raise
    assert isinstance(e, ValueError)            # old io.wamit raise
    ctx = e.context()
    assert ctx["error"] == "NonFiniteResult" and ctx["case"] == 3
    assert isinstance(errors.StaticsDivergence("x"), RuntimeError)
    assert isinstance(errors.ModelConfigError("x"), ValueError)
    assert all(issubclass(c, errors.RaftError)
               for c in errors.RECOVERABLE)
    assert errors.CacheCorruption not in errors.RECOVERABLE


def test_wamit_screen_raises_typed(tmp_path):
    from raft_tpu.io.wamit import read_wamit1

    p = tmp_path / "bad.1"
    p.write_text("10.0 1 1 0.5\n5.0 1 1 nan\n")
    with pytest.raises(errors.NonFiniteResult, match="non-finite"):
        read_wamit1(str(p))


# ---------------------------------------------------------------------------
# unit: ladder engine
# ---------------------------------------------------------------------------

def test_run_ladder_walks_and_records():
    calls = []
    attempts = []

    def fn():
        calls.append(_config.statics_mode())
        if len(calls) < 3:
            raise errors.StaticsDivergence("nope", case=0)
        return "ok"

    out = recovery.run_ladder("statics", "0", fn,
                              recovery.statics_ladder(),
                              recorder=attempts.append)
    assert out == "ok"
    # attempt 1 device, attempt 2 host, attempt 3 damped host succeeded
    assert calls == ["device", "host", "host"]
    assert [(a.step_from, a.step_to, a.outcome) for a in attempts] == [
        ("configured", "host_statics", "failed"),
        ("host_statics", "host_statics_damped", "recovered")]
    snap = obs.snapshot()
    series = snap["raft_tpu_recovery_attempts_total"]["series"]
    assert any(s["labels"]["outcome"] == "recovered" for s in series)
    # the damped rung exposed its clip override only inside the retry
    assert recovery.current("clip_scale", 1.0) == 1.0


def test_run_ladder_exhaustion_reraises():
    def fn():
        raise errors.NonFiniteResult("always")

    with pytest.raises(errors.NonFiniteResult):
        recovery.run_ladder("dynamics", "0", fn,
                            recovery.dynamics_ladder())


def test_run_ladder_disabled_is_bare():
    _config.set_recovery_mode("0")
    try:
        calls = []

        def fn():
            calls.append(1)
            raise errors.NonFiniteResult("x")

        with pytest.raises(errors.NonFiniteResult):
            recovery.run_ladder("dynamics", "0", fn,
                                recovery.dynamics_ladder())
        assert calls == [1]          # no retries with recovery off
    finally:
        _config.set_recovery_mode(None)


# ---------------------------------------------------------------------------
# unit: exec-cache corrupt entry -> delete-and-miss
# ---------------------------------------------------------------------------

def test_exec_cache_corrupt_entry_is_miss(tmp_path, monkeypatch):
    from raft_tpu.parallel import exec_cache

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", "1")
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path))
    exec_cache.reset_stats()
    fn = jax.jit(lambda x: x * 2.0)
    args = (jnp.arange(4.0),)
    key = exec_cache.make_key(fn="unit", model="sha256:t", nw=4)
    assert exec_cache.store(fn, args, key) is not None
    meta = exec_cache.load_meta(key)
    assert meta["bytes"] > 0 and len(meta["sha256"]) == 64
    assert exec_cache.load(key) is not None           # intact -> hit

    bin_path = os.path.join(str(tmp_path), key + ".bin")
    with open(bin_path, "r+b") as f:
        f.truncate(max(1, meta["bytes"] // 2))        # bit-rot
    assert exec_cache.load(key) is None               # corrupt -> miss
    assert exec_cache.stats()["corrupts"] == 1
    assert not os.path.exists(bin_path)               # purged
    assert exec_cache.load(key) is None               # plain miss now
    snap = obs.snapshot()
    events = {s["labels"]["event"]: s["value"]
              for s in snap["raft_exec_cache_events_total"]["series"]}
    assert events.get("corrupt") == 1


def test_exec_cache_injected_corruption(tmp_path, monkeypatch):
    from raft_tpu.parallel import exec_cache

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", "1")
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path))
    exec_cache.reset_stats()
    fn = jax.jit(lambda x: x + 1.0)
    key = exec_cache.make_key(fn="unit2", model="sha256:t", nw=4)
    assert exec_cache.store(fn, (jnp.arange(4.0),), key) is not None
    faults.install("corrupt@exec_cache:once")
    try:
        assert exec_cache.load(key) is None
        assert exec_cache.stats()["corrupts"] == 1
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# unit: journal round trip
# ---------------------------------------------------------------------------

def test_journal_retention_prunes_old_models(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_JOURNAL_MAX_MODELS", "2")
    base = str(tmp_path)
    for i, key in enumerate(("aaa", "bbb", "ccc")):
        j = recovery.CaseJournal(key, base_dir=base)
        j.store_case(0, {"case_metrics": {}, "mean_offset": np.zeros(6)})
        os.utime(j.dir, (i + 1, i + 1))      # deterministic age order
    # opening a NEW digest ("ddd") reserves its slot: of the 3 existing
    # dirs only the newest survives next to it
    recovery.prune_journals(base, keep="ddd")
    assert sorted(os.listdir(base)) == ["ccc"]
    j = recovery.CaseJournal("bbb", base_dir=base)
    j.store_case(0, {"case_metrics": {}, "mean_offset": np.zeros(6)})
    # re-opening an EXISTING digest prunes nothing while within bounds,
    # and the opened digest itself is never a pruning candidate
    recovery.prune_journals(base, keep="ccc")
    assert sorted(os.listdir(base)) == ["bbb", "ccc"]


def test_journal_roundtrip(tmp_path):
    j = recovery.CaseJournal("unitkey", base_dir=str(tmp_path))
    assert j.completed() == [] and j.load_case(0) is None
    j.store_case(0, {"case_metrics": {0: {"surge_std": 1.25}},
                     "mean_offset": np.arange(6.0)})
    j.store_case(2, {"case_metrics": {}, "mean_offset": np.zeros(6)})
    assert j.completed() == [0, 2]
    doc = j.load_case(0)
    assert doc["case_metrics"][0]["surge_std"] == 1.25
    assert np.all(doc["mean_offset"] == np.arange(6.0))
    # corrupt entry: deleted and treated as a miss
    with open(j._path(2), "wb") as f:
        f.write(b"not a pickle")
    assert j.load_case(2) is None
    assert j.completed() == [0]
    j.clear()
    assert j.completed() == []


def test_journal_corrupt_entries_counted_not_raised(tmp_path):
    """A torn pickle (crash mid-store) and a truncated one are misses:
    logged, deleted, and counted in raft_tpu_journal_corrupt_total —
    never an exception into the resume path."""
    import pickle

    j = recovery.CaseJournal("corrkey", base_dir=str(tmp_path))
    j.store_case(0, {"case_metrics": {}, "mean_offset": np.zeros(6)})
    j.store_case(1, {"case_metrics": {}, "mean_offset": np.zeros(6)})
    # torn write: the first half of a valid pickle (EOFError on load)
    whole = open(j._path(0), "rb").read()
    with open(j._path(0), "wb") as f:
        f.write(whole[: len(whole) // 2])
    # readable pickle of the wrong shape (not the journaled dict)
    with open(j._path(1), "wb") as f:
        pickle.dump(["not", "a", "journal", "record"], f)
    assert j.load_case(0) is None
    assert j.load_case(1) is None
    assert not os.path.exists(j._path(0))    # torn entry deleted
    snap = obs.snapshot()
    total = sum(s["value"] for s in
                snap["raft_tpu_journal_corrupt_total"]["series"])
    assert total == 2.0
    # a clean store afterwards works (the miss is recoverable)
    j.store_case(0, {"case_metrics": {}, "mean_offset": np.ones(6)})
    assert j.load_case(0) is not None


# ---------------------------------------------------------------------------
# integration: quarantine / ladder / resume on the coarse cylinder
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cyl_runs(tmp_path_factory):
    """Clean, faulted, resumed, and ladder-recovered runs of the coarse
    Vertical_cylinder model, with the obs facts captured per run."""
    journal_dir = str(tmp_path_factory.mktemp("journal"))
    os.environ["RAFT_TPU_JOURNAL_DIR"] = journal_dir
    state = {}
    try:
        obs.reset_all()
        faults.clear()

        m = Model(_cyl_design())
        m.analyzeCases()
        state["clean"] = {"ledger": m.last_ledger,
                          "manifest": m.last_manifest.to_dict(),
                          "results": m.results}
        # the clean run journaled everything — resume must exercise the
        # faulted run's journal, so start it fresh
        recovery.CaseJournal.for_model(m).clear()

        faults.install("nan@dynamics:case=1")      # persistent: no rung
        obs.reset_all()                            # can save case 1
        m = Model(_cyl_design())
        m.analyzeCases()
        faults.clear()
        state["faulted"] = {"ledger": m.last_ledger,
                            "manifest": m.last_manifest.to_dict(),
                            "snap": obs.snapshot(),
                            "transfers": obs.transfers.snapshot(),
                            "failed_cases": list(m.failed_cases)}

        obs.reset_all()
        m = Model(_cyl_design())
        m.analyzeCases(resume=True)
        state["resumed"] = {"ledger": m.last_ledger,
                            "manifest": m.last_manifest.to_dict(),
                            "agg": obs.aggregate(),
                            "snap": obs.snapshot()}

        faults.install("raise@kernel:case=0:once")
        obs.reset_all()
        m = Model(_cyl_design(ncases=1))
        m.analyzeCases()
        faults.clear()
        state["kernel_once"] = {"ledger": m.last_ledger,
                                "manifest": m.last_manifest.to_dict(),
                                "snap": obs.snapshot()}

        obs.reset_all()
        m = Model(_cyl_design(ncases=1))
        m.analyzeCases()
        state["clean1"] = {"ledger": m.last_ledger}
        yield state
    finally:
        os.environ.pop("RAFT_TPU_JOURNAL_DIR", None)
        faults.clear()
        obs.reset_all()


def test_quarantine_isolates_case(cyl_runs):
    """Acceptance: the faulted run completes, case 1 fails structured,
    cases 0/2 reproduce the clean run's ledger digests exactly."""
    clean, faulted = cyl_runs["clean"], cyl_runs["faulted"]
    failed = faulted["failed_cases"]
    assert len(failed) == 1 and failed[0]["case"] == 1
    assert failed[0]["error"] == "NonFiniteResult"
    assert failed[0]["phase"] == "dynamics"
    # structured record reaches manifest AND ledger extra
    assert faulted["manifest"]["extra"]["failed_cases"] == failed
    assert faulted["ledger"]["extra"]["failed_cases"] == failed
    # quarantined case appears as a structured ledger entry
    fe = _entry(faulted["ledger"], "case1/failed")
    assert fe["metrics"]["error"] == "NonFiniteResult"
    # neighbors completed with digests matching the clean run (1e-6
    # would suffice; the isolation is exact on CPU)
    dc, df = _digests(clean["ledger"]), _digests(faulted["ledger"])
    for key in ("case0/fowt0", "case0/system",
                "case2/fowt0", "case2/system"):
        assert dc[key] == df[key], key
    # the failed-case metric fired
    snap = cyl_runs["faulted"]["snap"]
    series = snap["raft_tpu_cases_failed_total"]["series"]
    assert series[0]["labels"]["phase"] == "dynamics"
    assert series[0]["value"] == 1.0


def test_ladder_attempts_recorded(cyl_runs):
    """The dynamics ladder walked jnp_solve -> damped_restart on the
    poisoned case, every transition recorded in the manifest and the
    raft_tpu_recovery_attempts_total metric."""
    mani = cyl_runs["faulted"]["manifest"]
    attempts = mani["extra"]["recovery"]["attempts"]
    chain = [(a["step_from"], a["step_to"], a["outcome"])
             for a in attempts if a["phase"] == "dynamics"]
    assert ("configured", "jnp_solve", "failed") in chain
    assert ("jnp_solve", "damped_restart", "failed") in chain
    snap = cyl_runs["faulted"]["snap"]
    series = snap["raft_tpu_recovery_attempts_total"]["series"]
    assert {(s["labels"]["from"], s["labels"]["to"])
            for s in series} >= {("configured", "jnp_solve"),
                                 ("jnp_solve", "damped_restart")}


def test_transfer_budget_with_quarantine(cyl_runs):
    """The faulted 3-case run stays within the per-case budget: the
    clean cases pull statics=1 / dynamics=4; the quarantined case's
    ladder attempts each pull through the same sanctioned exits (no
    unsanctioned pulls appear anywhere)."""
    xfers = cyl_runs["faulted"]["transfers"]["phases"]
    assert set(xfers) <= {"statics", "dynamics"}
    assert xfers["statics"]["events"] == 3          # one per statics solve
    # 2 clean cases x 4 + 3 attempts on the poisoned case x 4
    assert xfers["dynamics"]["events"] == 2 * 4 + 3 * 4


def test_resume_skips_completed(cyl_runs):
    """resume=True restores the journaled cases 0/2 (span-asserted: no
    statics/dynamics solves for them) and re-runs only failed case 1 —
    converging to the clean run's full ledger."""
    agg = cyl_runs["resumed"]["agg"]
    assert agg["case_resumed"][1] == 2
    assert agg["solveStatics"][1] == 1       # only case 1 re-solved
    assert agg["solveDynamics"][1] == 1
    mani = cyl_runs["resumed"]["manifest"]
    assert mani["extra"]["resumed_cases"] == [0, 2]
    assert mani["extra"]["failed_cases"] == []
    dc = _digests(cyl_runs["clean"]["ledger"])
    dr = _digests(cyl_runs["resumed"]["ledger"])
    assert set(dc) == set(dr)
    for key, dig in dc.items():
        assert dr[key] == dig, key
    snap = cyl_runs["resumed"]["snap"]
    assert snap["raft_tpu_cases_resumed_total"]["series"][0]["value"] == 2


def test_kernel_ladder_recovers_at_parity(cyl_runs):
    """A one-shot kernel failure degrades to the jnp solve and recovers
    with physics identical to a clean run (ladder parity gate)."""
    mani = cyl_runs["kernel_once"]["manifest"]
    attempts = mani["extra"]["recovery"]["attempts"]
    assert [(a["step_from"], a["step_to"], a["outcome"])
            for a in attempts] == [("configured", "jnp_solve",
                                    "recovered")]
    assert attempts[0]["error"] == "KernelFailure"
    assert mani["extra"]["failed_cases"] == []
    d1 = _digests(cyl_runs["clean1"]["ledger"])
    d2 = _digests(cyl_runs["kernel_once"]["ledger"])
    assert d1 == d2
    series = cyl_runs["kernel_once"]["snap"][
        "raft_tpu_recovery_attempts_total"]["series"]
    (s,) = series
    assert s["labels"] == {"from": "configured", "to": "jnp_solve",
                           "outcome": "recovered", "phase": "dynamics"}


def test_recovery_off_propagates(cyl_runs):
    """RAFT_TPU_RECOVERY=0 restores fail-fast: the typed error escapes
    analyzeCases and the manifest records a failed run."""
    _config.set_recovery_mode("0")
    faults.install("nan@dynamics:case=0")
    try:
        m = Model(_cyl_design(ncases=1))
        with pytest.raises(errors.NonFiniteResult):
            m.analyzeCases()
        assert m.last_manifest.status == "failed"
    finally:
        faults.clear()
        _config.set_recovery_mode(None)


def test_quarantine_clears_meandrift_for_next_case():
    """A potSecOrder case quarantined mid-dynamics must not leak its
    F_meandrift into the next case's statics — the neighbor's digest
    must match a clean run (the clean flow pops the drift forcing after
    the mean-drift statics re-solve; quarantine must too)."""
    def build():
        design = _cyl_design(ncases=2)
        design["platform"]["potSecOrder"] = 1
        design["platform"]["min_freq2nd"] = 0.05
        design["platform"]["max_freq2nd"] = 0.25
        ik = design["cases"]["keys"].index("wave_spectrum")
        for row in design["cases"]["data"]:
            row[ik] = "JONSWAP"      # a still sea has no drift forcing
        return design

    m = Model(build())
    m.analyzeCases()
    clean = _digests(m.last_ledger)

    faults.install("nan@dynamics:case=0")
    try:
        m = Model(build())
        m.analyzeCases()
    finally:
        faults.clear()
    assert [f["case"] for f in m.failed_cases] == [0]
    faulted = _digests(m.last_ledger)
    for key in ("case1/fowt0", "case1/system"):
        assert faulted[key] == clean[key], key


# ---------------------------------------------------------------------------
# integration: sweep batch quarantine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cyl_fowt():
    from raft_tpu.models.fowt import build_fowt

    design = load_design("Vertical_cylinder")
    w = np.arange(0.05, 0.5, 0.05) * 2 * np.pi
    return build_fowt(design, w,
                      depth=float(design["site"]["water_depth"]))


def test_sweep_lane_quarantine_parity(cyl_fowt):
    """A poisoned lane is detected on device, re-solved alone through
    the ladder, and spliced back at <=1e-6 parity with a clean batch;
    the healthy lanes and the clean-path pull budget are untouched."""
    from raft_tpu.parallel.sweep import sweep_cases

    rng = np.random.default_rng(7)
    nc = 4
    Hs = 2.0 + rng.random(nc)
    Tp = 8.0 + 2.0 * rng.random(nc)
    beta = np.deg2rad(rng.integers(0, 360, nc).astype(float))

    clean = sweep_cases(cyl_fowt, Hs, Tp, beta, nIter=6)
    clean_pulls = obs.transfers.counts("sweep")
    assert clean_pulls["events"] == 1               # one summary pull

    faults.install("nan@sweep:lane=2")
    try:
        out = sweep_cases(cyl_fowt, Hs, Tp, beta, nIter=6)
    finally:
        faults.clear()
    std_c = np.asarray(clean["std"])
    std_f = np.asarray(out["std"])
    assert np.all(np.isfinite(std_f))
    rel = np.abs(std_f - std_c) / np.maximum(np.abs(std_c), 1e-300)
    assert rel.max() <= 1e-6
    rel_xi = np.max(np.abs(np.asarray(out["Xi"])
                           - np.asarray(clean["Xi"])))
    assert rel_xi <= 1e-6 * max(1.0, np.abs(np.asarray(clean["Xi"])).max())
    # the faulted sweep used exactly one extra quarantine pull
    assert obs.transfers.counts("sweep")["events"] == clean_pulls[
        "events"] + 2
    snap = obs.snapshot()
    series = snap["raft_tpu_recovery_attempts_total"]["series"]
    assert any(s["labels"] == {"from": "batched", "to": "re_solve",
                               "outcome": "recovered", "phase": "sweep"}
               for s in series)


def test_sweep_quarantine_off_leaves_nan(cyl_fowt):
    from raft_tpu.parallel.sweep import sweep_cases

    faults.install("nan@sweep:lane=0")
    try:
        out = sweep_cases(cyl_fowt, np.array([2.0, 2.5]),
                          np.array([8.0, 8.5]), np.zeros(2),
                          nIter=6, quarantine="off")
    finally:
        faults.clear()
    std = np.asarray(out["std"])
    assert np.all(np.isnan(std[0])) and np.all(np.isfinite(std[1]))


# ---------------------------------------------------------------------------
# slow tier: the ISSUE acceptance scenario on the 3-case OC3 spar
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_oc3_three_case_acceptance(tmp_path):
    """With a fault injected into one case of a 3-case OC3 run: the run
    completes, the failed case appears as a structured record in
    manifest + ledger extra, the other cases' ledger digests match a
    clean run at 1e-6, and analyzeCases(resume=True) re-runs only the
    failed case — all within the pinned per-case transfer budget."""
    from raft_tpu.obs import ledger as L

    os.environ["RAFT_TPU_JOURNAL_DIR"] = str(tmp_path / "journal")
    try:
        def build():
            design = load_design("OC3spar")
            design.setdefault("settings", {})
            design["settings"].update({"min_freq": 0.02, "max_freq": 0.2})
            row0 = list(design["cases"]["data"][0])
            ih = design["cases"]["keys"].index("wave_height")
            rows = []
            for i in range(3):
                row = list(row0)
                row[ih] = float(row0[ih]) + 0.5 * i
                rows.append(row)
            design["cases"]["data"] = rows
            return design

        m = Model(build())
        m.analyzeCases()
        led_clean = m.last_ledger
        recovery.CaseJournal.for_model(m).clear()

        faults.install("nan@dynamics:case=1")
        obs.reset_all()
        transfers0 = obs.transfers.snapshot()
        m = Model(build())
        m.analyzeCases()
        faults.clear()
        led_faulted = m.last_ledger
        failed = m.failed_cases
        assert [f["case"] for f in failed] == [1]
        assert m.last_manifest.extra["failed_cases"] == failed
        assert led_faulted["extra"]["failed_cases"] == failed
        # clean-path budget holds for the surviving cases: statics=1
        # per statics solve and dynamics=4 per attempt
        xf = obs.transfers.delta(transfers0, obs.transfers.snapshot())
        assert xf["phases"]["statics"]["events"] == 3
        assert xf["phases"]["dynamics"]["events"] == 2 * 4 + 3 * 4

        report = L.diff(led_clean, led_faulted, tol_rel=1e-6)
        offending = {r["entry"] for r in report["regressions"]}
        # every moved/missing entry belongs to the quarantined case
        assert offending <= {"case1/fowt0", "case1/system",
                             "case1/failed"}
        assert set(report["added"]) == {"case1/failed"}
        assert set(report["removed"]) == {"case1/fowt0", "case1/system"}

        obs.reset_all()
        m = Model(build())
        m.analyzeCases(resume=True)
        agg = obs.aggregate()
        assert agg["case_resumed"][1] == 2
        assert agg["solveStatics"][1] == 1
        assert agg["solveDynamics"][1] == 1
        report = L.diff(led_clean, m.last_ledger, tol_rel=1e-6)
        assert report["ok"], report
    finally:
        os.environ.pop("RAFT_TPU_JOURNAL_DIR", None)
        faults.clear()


@pytest.mark.slow
def test_oc3_statics_ladder_host_fallback():
    """Statics divergence degrades device -> host Newton and recovers:
    the ladder records the transition and the recovered equilibrium
    matches a clean solve at 1e-6."""
    design = load_design("OC3spar")
    design.setdefault("settings", {})
    design["settings"].update({"min_freq": 0.02, "max_freq": 0.2})
    design["cases"]["data"] = design["cases"]["data"][:1]
    m = Model(design)
    case = dict(zip(design["cases"]["keys"], design["cases"]["data"][0]))
    X_clean = np.asarray(m.solveStatics(dict(case)))

    faults.install("raise@statics:case=0:once")
    attempts = []
    try:
        m._iCase = 0
        X = recovery.run_ladder(
            "statics", "0", lambda: m.solveStatics(dict(case)),
            recovery.statics_ladder(), recorder=attempts.append)
    finally:
        m._iCase = None
        faults.clear()
    assert [(a.step_from, a.step_to, a.outcome) for a in attempts] == [
        ("configured", "host_statics", "recovered")]
    scale = np.maximum(np.abs(X_clean), 1.0)
    assert np.all(np.abs(np.asarray(X) - X_clean) / scale < 1e-6)
