"""Cross-run regression sentinel canary (acceptance criterion).

The fast tier pins the sentinel's machinery against the two committed
golden ledgers under ``tests/golden/`` (OC3 spar + VolturnUS-S, coarse
frequency grids): the goldens must stay schema-valid and
content-addressed, ``obsctl diff`` of a golden against itself must
report zero regressions, perturbing one RAO digest beyond tolerance
must make ``obsctl`` exit nonzero, and ``obsctl selfcheck`` must pass —
so CI catches both physics drift and sentinel rot.

The slow tier closes the loop end-to-end: it reruns the exact coarse
OC3 configuration the golden was generated from, diffs the live ledger
against the golden, and runs the model twice back-to-back asserting the
two ledgers diff to zero regressions through the real ``obsctl`` exit
path.
"""
import copy
import importlib.util
import json
import os

import pytest

from raft_tpu.obs import ledger as L

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
GOLDENS = {
    "OC3spar": os.path.join(GOLDEN_DIR, "oc3spar_coarse.ledger.json"),
    "VolturnUS-S": os.path.join(GOLDEN_DIR, "volturnus_coarse.ledger.json"),
}
#: the coarse grid the goldens were generated on (one load case)
GOLDEN_FREQ = {"min_freq": 0.02, "max_freq": 0.2}


def _load_obsctl():
    """Import tools/obsctl.py (tools/ is not a package) once per session."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "obsctl.py")
    spec = importlib.util.spec_from_file_location("obsctl", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def obsctl():
    return _load_obsctl()


def _run_coarse(name):
    """One analyzeCases run of design ``name`` on the golden grid;
    returns the resulting ledger."""
    from raft_tpu.io.designs import load_design
    from raft_tpu.model import Model

    design = load_design(name)
    design.setdefault("settings", {})
    design["settings"].update(GOLDEN_FREQ)
    design["cases"]["data"] = design["cases"]["data"][:1]
    model = Model(design)
    model.analyzeCases()
    return model.last_ledger


# ---------------------------------------------------------------------------
# fast tier: the committed goldens and the obsctl exit paths
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(GOLDENS))
def test_golden_ledger_is_valid(name):
    led = L.load_ledger(GOLDENS[name])
    assert L.validate_ledger(led) == []
    keys = {e["key"] for e in led["entries"]}
    assert "case0/fowt0" in keys and "case0/system" in keys
    fowt0 = next(e for e in led["entries"] if e["key"] == "case0/fowt0")
    for metric in ("rao_mag_max_surge", "rao_phase_peak_pitch",
                   "mean_heave", "std_surge", "drag_iters"):
        assert metric in fowt0["metrics"], f"golden lost {metric}"


def test_goldens_are_distinct_designs():
    a = L.load_ledger(GOLDENS["OC3spar"])
    b = L.load_ledger(GOLDENS["VolturnUS-S"])
    assert a["digest"] != b["digest"]
    assert not L.diff(a, b)["ok"]      # different platforms must not diff clean


@pytest.mark.parametrize("name", sorted(GOLDENS))
def test_obsctl_diff_golden_vs_itself_is_clean(obsctl, name, capsys):
    rc = obsctl.main(["diff", GOLDENS[name], GOLDENS[name]])
    assert rc == 0
    assert "digests identical" in capsys.readouterr().out


def test_perturbed_rao_digest_exits_nonzero(obsctl, tmp_path, capsys):
    """Acceptance: perturbing one RAO metric by > tolerance makes obsctl
    exit nonzero; the same perturbation passes under a loose tolerance."""
    led = L.load_ledger(GOLDENS["OC3spar"])
    bad = copy.deepcopy(led)
    e = next(x for x in bad["entries"] if x["key"] == "case0/fowt0")
    e["metrics"]["rao_mag_max_surge"] *= 1.0 + 1e-4     # >> 1e-6 tol
    e["digest"] = L.digest_metrics(e["metrics"])
    bad["digest"] = None
    path = L.write_ledger(bad, str(tmp_path / "perturbed.ledger.json"))

    rc = obsctl.main(["diff", GOLDENS["OC3spar"], path, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    (reg,) = report["regressions"]
    assert reg["metric"] == "rao_mag_max_surge"
    # check mode agrees, and a per-metric tolerance override clears it
    assert obsctl.main(["check", "--baseline", GOLDENS["OC3spar"],
                        path]) == 1
    assert obsctl.main(["check", "--baseline", GOLDENS["OC3spar"], path,
                        "--tol", "rao_mag_*=1e-3"]) == 0
    capsys.readouterr()


def test_tampered_golden_fails_check(obsctl, tmp_path, capsys):
    """Content addressing: editing metrics without re-digesting is
    caught by `obsctl check` even when the values would be in tolerance."""
    led = L.load_ledger(GOLDENS["VolturnUS-S"])
    led["entries"][0]["metrics"]["drag_iters"] = 999
    path = str(tmp_path / "tampered.ledger.json")
    with open(path, "w") as f:
        json.dump(led, f)
    rc = obsctl.main(["check", "--baseline", GOLDENS["VolturnUS-S"], path])
    assert rc == 1
    assert "digest mismatch" in capsys.readouterr().out


def test_obsctl_trend_over_goldens(obsctl, capsys):
    rc = obsctl.main(["trend", GOLDEN_DIR])
    out = capsys.readouterr().out
    assert rc == 0
    assert "oc3spar_coarse.ledger.json" in out
    assert "ledger/analyzeCases" in out


def test_residual_metrics_get_tolerance_floor():
    """Residual-class metrics (solver convergence diagnostics at
    machine-epsilon magnitudes) compare with a relative tolerance FLOOR
    instead of the exact ledger tolerance: the observed cross-host
    statics_residual jitter (4.5638e-7 vs 4.5607e-7, a ~7e-4 relative
    "drift" of pure noise) must NOT flag, while the same relative move
    on a physics metric must."""
    led = L.new_ledger("t", run_id="a")
    L.add_entry(led, "case0/system", {"statics_residual": 4.5638e-7,
                                      "mean_offset": 10.0})
    L.finalize(led)
    moved = L.new_ledger("t", run_id="b")
    L.add_entry(moved, "case0/system", {"statics_residual": 4.5607e-7,
                                        "mean_offset": 10.0})
    L.finalize(moved)
    assert L.diff(led, moved, tol_rel=1e-6)["ok"]

    # the identical relative move on a non-residual metric still flags
    drifted = L.new_ledger("t", run_id="c")
    L.add_entry(drifted, "case0/system",
                {"statics_residual": 4.5638e-7,
                 "mean_offset": 10.0 * (1 + 6.8e-4)})
    L.finalize(drifted)
    rep = L.diff(led, drifted, tol_rel=1e-6)
    assert not rep["ok"]
    assert rep["regressions"][0]["metric"] == "mean_offset"

    # an explicit per-metric override beats the floor (pin-it-exactly)
    rep = L.diff(led, moved, tol_rel=1e-6,
                 per_metric={"statics_residual": 1e-9})
    assert not rep["ok"]
    # a residual drift ABOVE the floor still flags
    blown = L.new_ledger("t", run_id="d")
    L.add_entry(blown, "case0/system", {"statics_residual": 4.6e-5,
                                        "mean_offset": 10.0})
    L.finalize(blown)
    assert not L.diff(led, blown, tol_rel=1e-6)["ok"]


def test_obsctl_selfcheck(obsctl, capsys):
    """CI guard: the synthetic round-trip through diff/check/trend."""
    rc = obsctl.main(["selfcheck"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "obsctl selfcheck: OK" in out


# ---------------------------------------------------------------------------
# slow tier: live reruns against the goldens (the actual canary)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(GOLDENS))
def test_live_run_matches_golden(name):
    """Physics drift canary: rerunning the exact golden configuration
    must reproduce every digested metric to 1e-6 relative."""
    led = _run_coarse(name)
    golden = L.load_ledger(GOLDENS[name])
    report = L.diff(golden, led, tol_rel=1e-6)
    assert report["ok"], L.format_diff(report)


@pytest.mark.slow
def test_back_to_back_runs_diff_clean_through_obsctl(obsctl, tmp_path,
                                                    capsys):
    """Acceptance: obsctl diff on two ledgers from back-to-back identical
    CPU runs of the OC3 example reports zero regressions."""
    pa = L.write_ledger(_run_coarse("OC3spar"),
                        str(tmp_path / "run_a.ledger.json"))
    pb = L.write_ledger(_run_coarse("OC3spar"),
                        str(tmp_path / "run_b.ledger.json"))
    rc = obsctl.main(["diff", pa, pb])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 regression(s)" in out


def test_check_rejects_invalid_baseline(obsctl, tmp_path, capsys):
    """A tampered BASELINE is bad input (exit 2), not a regression."""
    led = L.load_ledger(GOLDENS["OC3spar"])
    led["entries"][0]["metrics"]["drag_iters"] = 999   # no re-digest
    bad_base = str(tmp_path / "bad_base.ledger.json")
    with open(bad_base, "w") as f:
        json.dump(led, f)
    with pytest.raises(SystemExit) as exc:
        obsctl.main(["check", "--baseline", bad_base, GOLDENS["OC3spar"]])
    assert exc.value.code == 2
    assert "baseline ledger is invalid" in capsys.readouterr().err


def test_diff_directory_arg_is_bad_invocation(obsctl, capsys):
    """A directory where a file is expected exits 2, not 1."""
    with pytest.raises(SystemExit) as exc:
        obsctl.main(["diff", GOLDEN_DIR, GOLDENS["OC3spar"]])
    assert exc.value.code == 2
    capsys.readouterr()


def test_manifest_removed_key_is_regression(obsctl, tmp_path, capsys):
    """A metric/phase the newer run LOST flags the manifest diff; one it
    gained does not."""
    man_a = {"schema": "raft_tpu.run_manifest/v1", "run_id": "a",
             "kind": "bench", "status": "ok", "duration_s": 10.0,
             "phases": [{"name": "solve", "total_s": 8.0, "calls": 1}],
             "metrics": {}, "extra": {}}
    man_b = json.loads(json.dumps(man_a))
    man_b["run_id"] = "b"
    man_b["phases"] = [{"name": "other", "total_s": 8.0, "calls": 1}]
    pa, pb = str(tmp_path / "a.manifest.json"), str(tmp_path /
                                                   "b.manifest.json")
    json.dump(man_a, open(pa, "w"))
    json.dump(man_b, open(pb, "w"))
    assert obsctl.main(["diff", pa, pb]) == 1    # solve phase vanished
    assert obsctl.main(["diff", pb, pa]) == 1    # other phase vanished
    man_b["phases"].insert(0, {"name": "solve", "total_s": 8.0,
                               "calls": 1})
    json.dump(man_b, open(pb, "w"))
    assert obsctl.main(["diff", pa, pb]) == 0    # superset: added only
    capsys.readouterr()
