"""The content-addressed result tier (raft_tpu/serve/resultstore.py).

Unit tier (stub batch engines, no solves): store roundtrip + the
integrity ladder (torn put, byte corruption, stale-payload rejection,
delete-and-miss accounting), the fault grammar, neighbor search +
quarantine, read-through hits at admission (memory speed, batch window
bypassed, across restarts and replicas, bit-for-bit), single-flight
coalescing (exactly D solves under a concurrent duplicate storm,
per-follower deadlines, failure fan-out, replay coalescing), the
``fetch_rdigest`` LRU-eviction fall-through (store, then journal), the
router's local store consult, and the trend-store facts / SLO rules.

Integration tier (one coarse Vertical_cylinder model): neighbor
warm-start parity — audited warm batches deliver cold-identical
digests with strictly fewer seeded iterations on a smooth grid, and a
deliberately poisoned neighbor seed trips the typed
``WarmStartRejected`` fallback with no digest deviation — plus the
ISSUE-acceptance duplicate-storm soak (``serve.soak.run_storm``).
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from raft_tpu import errors, obs
from raft_tpu.obs.ledger import digest_metrics
from raft_tpu.serve import ServeConfig, SweepService
from raft_tpu.serve import journal as wal
from raft_tpu.serve.resultstore import ResultStore
from raft_tpu.testing import faults


def _payload(Hs=2.0, Tp=8.0, beta=0.0, tenant="default", iters=3,
             converged=True, seed=1.0):
    std = [float(seed) * (i + 1) for i in range(6)]
    rdigest = wal.request_digest(Hs, Tp, beta, tenant)
    digest = digest_metrics({"std": std, "iters": int(iters),
                             "converged": bool(converged)})
    return {"rdigest": rdigest, "digest": digest, "std": std,
            "iters": int(iters), "converged": bool(converged),
            "tenant": tenant, "Hs": float(Hs), "Tp": float(Tp),
            "beta": float(beta)}


def _cfg(tmp_path, **kw):
    base = dict(queue_max=16, batch_cases=4, window_s=0.02,
                batch_deadline_s=10.0, retry_base_s=0.01,
                degrade_after=99, store_dir=str(tmp_path / "store"))
    base.update(kw)
    return ServeConfig(**base)


def stub_factory(mode, fowt, ncases, **kw):
    """Instant deterministic engine: std row = Hs replicated."""
    def run(Hs, Tp, beta):
        Hs = np.asarray(Hs)
        return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                "iters": np.full(len(Hs), 3),
                "converged": np.ones(len(Hs), bool)}
    run.ncases = ncases
    run.cache_state = "stub"
    return run


def counting_stub_factory(calls):
    def factory(mode, fowt, ncases, **kw):
        base = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            calls.append(np.asarray(Hs).tolist())
            return base(Hs, Tp, beta)
        run.ncases = ncases
        run.cache_state = "stub"
        return run
    return factory


# ---------------------------------------------------------------------------
# unit: the store itself
# ---------------------------------------------------------------------------

def test_store_roundtrip_sidecar_and_seed(tmp_path):
    s = ResultStore(str(tmp_path), keep_xi=True)
    p = _payload()
    xi = (np.arange(12.0) + 2j).reshape(6, 2)
    assert s.put(p, xi=xi)
    stem = p["rdigest"].rsplit(":", 1)[-1]
    side_path = tmp_path / f"{stem}.sum"
    assert (tmp_path / f"{stem}.json").exists()
    assert side_path.exists() and (tmp_path / f"{stem}.xi").exists()
    side = json.loads(side_path.read_text())
    assert side["sha256"] and side["size"] > 0 and side["xi_sha256"]
    doc = s.get(p["rdigest"])
    assert doc["std"] == p["std"] and doc["digest"] == p["digest"]
    assert np.array_equal(s.get_xi(p["rdigest"]), xi)
    assert s.get_by_digest(p["digest"])["rdigest"] == p["rdigest"]
    # a fresh handle rebuilds the neighbor index from sidecars alone
    s2 = ResultStore(str(tmp_path), keep_xi=True)
    assert len(s2) == 1
    assert s2.nearest(2.1, 8.0, 0.0, "default", radius=1.0)[0] \
        == p["rdigest"]
    st = s.stats()
    assert st["puts"] == 1 and st["corrupt"] == 0 and st["seeds"] == 1


def test_store_torn_put_reads_as_counted_miss(tmp_path):
    s = ResultStore(str(tmp_path))
    p = _payload()
    assert s.put(p)
    stem = p["rdigest"].rsplit(":", 1)[-1]
    (tmp_path / f"{stem}.sum").unlink()      # the crash-before-sidecar
    # within TORN_GRACE_S the payload may be a concurrent put mid-
    # commit: a plain miss that must NOT delete the entry
    assert s.get(p["rdigest"]) is None
    assert (tmp_path / f"{stem}.json").exists()
    assert s.stats()["corrupt"] == 0 and s.stats()["misses"] == 1
    # past the grace window it is a genuine torn put: delete-and-miss
    old = time.time() - 2 * ResultStore.TORN_GRACE_S
    os.utime(tmp_path / f"{stem}.json", (old, old))
    assert s.get(p["rdigest"]) is None
    assert not (tmp_path / f"{stem}.json").exists()
    assert s.stats()["corrupt"] == 1
    # a genuinely absent key is a plain miss, not corruption
    assert s.get(_payload(Hs=9.0)["rdigest"]) is None
    assert s.stats()["misses"] == 2 and s.stats()["corrupt"] == 1


def test_store_corrupt_bytes_delete_and_miss_and_strict(tmp_path):
    s = ResultStore(str(tmp_path))
    p = _payload()
    s.put(p)
    stem = p["rdigest"].rsplit(":", 1)[-1]
    path = tmp_path / f"{stem}.json"
    raw = bytearray(path.read_bytes())
    raw[0] ^= 0xFF
    path.write_bytes(bytes(raw))
    assert s.get(p["rdigest"]) is None       # delete-and-miss
    assert not path.exists()
    assert s.stats()["corrupt"] == 1
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_result_store_corrupt_total"]["series"]
    assert sum(x["value"] for x in series) >= 1
    # strict mode surfaces the typed subclass instead
    s.put(p)
    path.write_bytes(b"garbage")
    with pytest.raises(errors.ResultStoreCorrupt) as exc:
        s.get(p["rdigest"], strict=True)
    assert isinstance(exc.value, errors.CacheCorruption)


def test_store_fault_corrupt_and_stale_rejected(tmp_path):
    """corrupt@resultstore drives the byte-level reject; stale@ serves
    a byte-consistent but digest-mismatched payload that ONLY the
    semantic check can catch — both end delete-and-miss."""
    s = ResultStore(str(tmp_path))
    p, q = _payload(), _payload(Hs=3.0, seed=2.0)
    s.put(p)
    s.put(q)
    stem_p = p["rdigest"].rsplit(":", 1)[-1]
    faults.install(f"corrupt@resultstore:entry={stem_p}")
    try:
        assert s.get(q["rdigest"]) is not None   # other entries fine
        assert s.get(p["rdigest"]) is None
        assert s.stats()["corrupt"] == 1
    finally:
        faults.clear()
    faults.install("stale@resultstore")
    try:
        assert s.get(q["rdigest"]) is None
        assert s.stats()["corrupt"] == 2
    finally:
        faults.clear()
    # both attacked entries are gone; the store itself still serves
    assert len(s) == 0


def test_faults_resultstore_grammar():
    specs = faults.parse(
        "corrupt@resultstore:entry=abc,stale@resultstore:once,"
        "corrupt@resultstore")
    assert [f["action"] for f in specs] == ["corrupt", "stale",
                                           "corrupt"]
    assert specs[0]["match"] == {"entry": "abc"}
    # unsupported combos rejected at parse time (never a silent no-op)
    assert faults.parse("stale@serve,stale@journal,nan@resultstore,"
                        "torn@resultstore,hang@resultstore,"
                        "kill@resultstore,drop@resultstore") == []


def test_nearest_respects_radius_tenant_and_quarantine(tmp_path):
    s = ResultStore(str(tmp_path), keep_xi=True)
    near = _payload(Hs=2.0, Tp=8.0)
    far = _payload(Hs=5.0, Tp=11.0)
    other = _payload(Hs=2.05, Tp=8.0, tenant="acme")
    xi = np.ones((6, 2), complex)
    for p in (near, far, other):
        s.put(p, xi=xi)
    got = s.nearest(2.1, 8.1, 0.0, "default", radius=1.0)
    assert got[0] == near["rdigest"] and got[1] < 0.2
    assert s.nearest(9.0, 3.0, 0.0, "default", radius=1.0) is None
    assert s.nearest(2.1, 8.0, 0.0, "acme", radius=1.0)[0] \
        == other["rdigest"]
    s.quarantine(near["rdigest"])
    assert s.nearest(2.1, 8.1, 0.0, "default", radius=1.0) is None
    assert s.stats()["quarantined"] == 1
    # a seed-less entry never seeds
    s2 = ResultStore(str(tmp_path / "noxi"), keep_xi=False)
    s2.put(_payload())
    assert s2.nearest(2.0, 8.0, 0.0, "default", radius=1.0) is None


def _nearest_loop_reference(index, quarantined, Hs, Tp, beta, tenant,
                            radius, exclude=()):
    """The pre-vectorization semantics of ``nearest()``: one Python
    loop over every index entry.  Kept as the parity oracle and the
    baseline the micro-benchmark below pins the NumPy scan against."""
    best = None
    for rd, m in index.items():
        if (not m.get("xi") or rd in quarantined
                or str(m.get("tenant")) != tenant or rd in exclude):
            continue
        d = ((float(m["Hs"]) - Hs) ** 2 + (float(m["Tp"]) - Tp) ** 2
             + (float(m["beta"]) - beta) ** 2) ** 0.5
        if d <= radius and (best is None or d < best[1]):
            best = (rd, d)
    return best


def test_nearest_vectorized_parity_and_speed(tmp_path):
    """The vectorized ``nearest()`` must (a) agree with the Python-loop
    reference on every query over a large synthetic index, and (b) be
    pinned meaningfully faster — the whole point of caching parallel
    NumPy views is that a neighbor query over thousands of entries
    stops costing a per-entry interpreter loop at admission time."""
    n = 8000
    rng = np.random.default_rng(7)
    s = ResultStore(str(tmp_path), keep_xi=True)
    index = {}
    for i in range(n):
        tenant = ("default", "acme", "zeta")[i % 3]
        index[f"sha256:{i:08x}"] = {
            "Hs": float(rng.uniform(1.0, 12.0)),
            "Tp": float(rng.uniform(5.0, 18.0)),
            "beta": float(rng.uniform(-0.5, 0.5)),
            "tenant": tenant, "digest": f"d{i}",
            "xi": bool(i % 7),            # ~14% seed-less
        }
    quarantined = {f"sha256:{i:08x}" for i in range(0, n, 11)}
    s._index = dict(index)
    s._quarantined = set(quarantined)
    # the synthetic index has no on-disk sidecars backing it; pin the
    # refresh out so the scan itself (what this test times) is isolated
    # from the directory walk
    s._refresh_index_locked = lambda force=False: None

    queries = [(float(rng.uniform(1.0, 12.0)),
                float(rng.uniform(5.0, 18.0)),
                float(rng.uniform(-0.5, 0.5)),
                ("default", "acme", "zeta")[k % 3])
               for k in range(40)]
    exclude = (f"sha256:{5:08x}", "sha256:not-present")

    # -- parity: every query, including misses and exclusions ---------
    for Hs, Tp, beta, tenant in queries:
        for radius in (0.05, 2.0, 50.0):
            want = _nearest_loop_reference(
                index, quarantined, Hs, Tp, beta, tenant, radius,
                exclude)
            got = s.nearest(Hs, Tp, beta, tenant, radius,
                            exclude=exclude)
            if want is None:
                assert got is None
            else:
                assert got is not None and got[0] == want[0]
                assert got[1] == pytest.approx(want[1], rel=1e-12)

    # -- micro-benchmark: pinned speedup over the loop reference ------
    s.nearest(6.0, 10.0, 0.0, "default", 50.0)   # build the cache once
    t0 = time.perf_counter()
    for Hs, Tp, beta, tenant in queries:
        s.nearest(Hs, Tp, beta, tenant, 50.0)
    t_vec = time.perf_counter() - t0
    t0 = time.perf_counter()
    for Hs, Tp, beta, tenant in queries:
        _nearest_loop_reference(index, quarantined, Hs, Tp, beta,
                                tenant, 50.0)
    t_loop = time.perf_counter() - t0
    # the observed gap is ~20-40x; 2x keeps the pin loose enough for a
    # loaded CI box while still catching a regression to a Python loop
    assert t_vec < t_loop / 2.0, (t_vec, t_loop)

    # a mutation invalidates the cached arrays: quarantining the
    # current best must be visible to the very next query
    best = s.nearest(6.0, 10.0, 0.0, "acme", 50.0)
    s.quarantine(best[0])
    after = s.nearest(6.0, 10.0, 0.0, "acme", 50.0)
    assert after is None or after[0] != best[0]


def test_corpus_export_deterministic_and_skips_invalid(tmp_path):
    """The surrogate's training feed: exporting the same store twice —
    with a torn-put orphan and a quarantined seed both present — must
    yield byte-identical arrays, identical skip accounting, and leave
    the store untouched (the exporter is an offline reader, not the
    serving ladder's delete-and-miss discipline)."""
    from raft_tpu.serve import surrogate

    s = ResultStore(str(tmp_path), keep_xi=True)
    rows = [_payload(Hs=2.0 + 0.5 * i, Tp=7.0 + 0.3 * i, beta=0.01 * i,
                     seed=1.0 + i) for i in range(12)]
    for p in rows:
        s.put(p, xi=np.ones((6, 2), complex))
    s.put(_payload(Hs=3.3, Tp=9.9, tenant="acme"))   # other tenant
    # a torn put: payload with no certifying .sum sidecar (a crashed
    # writer) — counted, never touched
    with open(os.path.join(str(tmp_path), "deadbeef.json"), "w") as f:
        json.dump({"torn": True}, f)
    # a quarantined seed: the divergence guard rejected its physics,
    # so it must never become training data
    s.quarantine(rows[3]["rdigest"])

    c1, c2 = {}, {}
    X1, Y1, rds1 = surrogate.export_corpus(s, counts=c1)
    X2, Y2, rds2 = surrogate.export_corpus(s, counts=c2)
    assert rds1 == rds2 == sorted(rds1)
    assert X1.dtype == np.float64 and X1.shape == (11, 3)
    assert X1.tobytes() == X2.tobytes()        # byte identity, not approx
    assert Y1.tobytes() == Y2.tobytes()
    assert surrogate.corpus_digest(X1, Y1) \
        == surrogate.corpus_digest(X2, Y2)
    assert c1 == c2
    assert c1["exported"] == 11 == len(rds1)
    assert c1["skipped_orphan"] == 1
    assert c1["skipped_quarantined"] == 1
    assert c1["skipped_corrupt"] == 0 and c1["skipped_degraded"] == 0
    assert rows[3]["rdigest"] not in rds1
    # the tenant filter keeps corpora per-tenant
    assert all(s._index[rd].get("tenant") == "default" for rd in rds1)
    # nothing mutated: the orphan survives and the quarantined
    # payload is still readable (only its SEED was revoked)
    assert os.path.exists(os.path.join(str(tmp_path), "deadbeef.json"))
    assert s.get(rows[3]["rdigest"]) is not None


def test_warm_watchdog_window_covers_audit_double_solve(tmp_path,
                                                        monkeypatch):
    """An audited (or guard-fallback) warm batch legitimately runs TWO
    solves — the watchdog window must cover both, or every audit would
    be abandoned and accrue hang strikes toward quarantine."""
    from raft_tpu.serve.watchdog import Watchdog

    windows = []
    real_arm = Watchdog.arm

    def arm(self, deadline_ts, on_expire):
        windows.append(deadline_ts - time.monotonic())
        return real_arm(self, deadline_ts, on_expire)

    monkeypatch.setattr(Watchdog, "arm", arm)

    def warm_stub(mode, fowt, ncases, **kw):
        def run(Hs, Tp, beta, Xi0=None):
            Hs = np.asarray(Hs)
            return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                    "iters": np.full(len(Hs), 3),
                    "converged": np.ones(len(Hs), bool),
                    "Xi": np.zeros((len(Hs), 6, 2), complex)}
        run.ncases = ncases
        run.cache_state = "stub"
        run.warm_start = True
        run.nw = 2
        run.xistart = 0.1
        return run

    cfg = _cfg(tmp_path, warm_start=True, warm_audit_every=1,
               batch_deadline_s=8.0)
    svc = SweepService(None, cfg, runner_factory=warm_stub)
    svc.start()
    try:
        assert svc.submit(2.0, 8.0, 0.0).result(10.0).ok
    finally:
        svc.stop(drain=False, timeout=5.0)
    assert windows and windows[-1] > 1.5 * cfg.batch_deadline_s


def test_quarantine_is_durable_across_handles(tmp_path):
    """A quarantined seed must stay out of nearest() after a restart
    and for sibling replicas sharing the directory — the .xi file is
    unlinked, not just flagged in this process's memory."""
    s = ResultStore(str(tmp_path), keep_xi=True)
    p = _payload(Hs=2.0, Tp=8.0)
    s.put(p, xi=np.ones((6, 2), complex))
    # a sibling replica over the same directory sees the seed...
    sib = ResultStore(str(tmp_path), keep_xi=True)
    assert sib.nearest(2.1, 8.0, 0.0, "default", radius=1.0)[0] \
        == p["rdigest"]
    s.quarantine(p["rdigest"])
    stem = p["rdigest"].rsplit(":", 1)[-1]
    assert not (tmp_path / f"{stem}.xi").exists()
    # ...but never after the quarantine: neither the already-running
    # sibling (index refresh) nor a fresh post-restart handle
    assert sib.nearest(2.1, 8.0, 0.0, "default", radius=1.0) is None
    fresh = ResultStore(str(tmp_path), keep_xi=True)
    assert fresh.nearest(2.1, 8.0, 0.0, "default", radius=1.0) is None
    # the payload itself stays readable — only seeding is revoked
    assert fresh.get(p["rdigest"])["digest"] == p["digest"]


def test_index_refreshes_across_processes(tmp_path):
    """get_by_digest()/nearest() must see entries written by a sibling
    process after this handle's first index load (the router consults
    its local store for a dead replica's results)."""
    reader = ResultStore(str(tmp_path), keep_xi=True)
    assert len(reader) == 0                  # index loaded while empty
    writer = ResultStore(str(tmp_path), keep_xi=True)
    p = _payload(Hs=3.0, Tp=9.0)
    writer.put(p, xi=np.ones((6, 2), complex))
    assert reader.get_by_digest(p["digest"])["rdigest"] == p["rdigest"]
    assert reader.nearest(3.1, 9.0, 0.0, "default", radius=1.0)[0] \
        == p["rdigest"]
    assert len(reader) == 1


# ---------------------------------------------------------------------------
# unit: read-through admission + single-flight coalescing
# ---------------------------------------------------------------------------

def test_store_hit_bypasses_batch_window_and_restarts(tmp_path):
    calls = []
    cfg = _cfg(tmp_path)
    svc = SweepService(runner_factory=counting_stub_factory(calls),
                       config=cfg)
    svc.start()
    r0 = svc.submit(2.0, 8.0, 0.0).result(10.0)
    assert r0.ok and r0.source == "solved"
    svc.stop()
    # a NEW service on the same store, worker never started: the exact
    # repeat resolves AT ADMISSION — no queue, no batch window, no WAL
    svc2 = SweepService(runner_factory=counting_stub_factory(calls),
                        config=cfg)
    t = svc2.submit(2.0, 8.0, 0.0)
    assert t.done()
    r1 = t.result(0.0)
    assert r1.source == "cached"
    assert r1.digest == r0.digest and r1.std == r0.std   # bit-for-bit
    svc2.start()
    s = svc2.stop()
    assert s["store_hits"] == 1 and s["admitted"] == 0
    assert s["store_hit_ratio"] == 1.0
    assert s["read_p50_ms"] is not None
    assert len(calls) == 1                    # one solve, ever
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_result_store_reads_total"]["series"]
    assert any(x["labels"].get("source") == "store" for x in series)


def test_single_flight_concurrent_storm_exactly_d_solves(tmp_path):
    calls = []
    gate = threading.Event()

    def factory(mode, fowt, ncases, **kw):
        def run(Hs, Tp, beta):
            gate.wait(10.0)
            Hs = np.asarray(Hs)
            calls.append(Hs.tolist())
            return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                    "iters": np.full(len(Hs), 3),
                    "converged": np.ones(len(Hs), bool)}
        run.ncases = ncases
        run.cache_state = "stub"
        return run

    svc = SweepService(runner_factory=factory, config=_cfg(tmp_path))
    n, d = 24, 3
    tickets = [None] * n
    barrier = threading.Barrier(8)

    def storm(k):
        barrier.wait(5.0)
        for i in range(k, n, 8):
            tickets[i] = svc.submit(1.0 + (i % d), 8.0, 0.0)
    threads = [threading.Thread(target=storm, args=(k,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    svc.start()
    gate.set()
    results = [t.result(20.0) for t in tickets]
    assert all(r.ok for r in results)
    # duplicates bit-identical to their primary
    by_hs = {}
    for r in results:
        by_hs.setdefault(r.std[0], set()).add(r.digest)
    assert all(len(v) == 1 for v in by_hs.values())
    s = svc.stop()
    distinct_solved = {h for lanes in calls for h in lanes}
    assert len(distinct_solved) == d          # exactly D distinct solves
    assert s["coalesced"] == n - d
    assert s["completed"] == n


def test_single_flight_follower_deadline_and_failure_fanout(tmp_path):
    gate = threading.Event()

    def slow_factory(mode, fowt, ncases, **kw):
        def run(Hs, Tp, beta):
            gate.wait(10.0)
            Hs = np.asarray(Hs)
            return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                    "iters": np.full(len(Hs), 3),
                    "converged": np.ones(len(Hs), bool)}
        run.ncases = ncases
        run.cache_state = "stub"
        return run

    svc = SweepService(runner_factory=slow_factory,
                       config=_cfg(tmp_path, batch_cases=1))
    svc.start()
    prim = svc.submit(2.0, 8.0, 0.0)
    time.sleep(0.1)                          # the solve is in flight
    fol_ok = svc.submit(2.0, 8.0, 0.0)
    fol_dead = svc.submit(2.0, 8.0, 0.0, deadline_s=0.05)
    time.sleep(0.2)                          # follower deadline lapses
    gate.set()
    assert prim.result(10.0).ok
    r_ok = fol_ok.result(10.0)
    assert r_ok.ok and r_ok.source == "coalesced"
    r_dead = fol_dead.result(10.0)
    assert not r_dead.ok
    assert r_dead.error["error"] == "DeadlineExceeded"
    s = svc.stop()
    assert s["coalesced"] == 2

    # failure fan-out: the primary's typed terminal failure reaches
    # every follower (budget-exhausted NonFiniteResult here)
    def nan_factory(mode, fowt, ncases, **kw):
        def run(Hs, Tp, beta):
            Hs = np.asarray(Hs)
            return {"std": np.full((len(Hs), 6), np.nan),
                    "iters": np.full(len(Hs), 3),
                    "converged": np.zeros(len(Hs), bool)}
        run.ncases = ncases
        run.cache_state = "stub"
        return run

    svc = SweepService(runner_factory=nan_factory,
                       config=_cfg(tmp_path / "b", retry_base_s=0.0))
    p = svc.submit(2.0, 8.0, 0.0)
    f = svc.submit(2.0, 8.0, 0.0)
    svc.start()
    rp, rf = p.result(20.0), f.result(20.0)
    assert not rp.ok and not rf.ok
    assert rf.error["error"] == rp.error["error"] == "NonFiniteResult"
    svc.stop()


def test_recover_coalesces_duplicate_pending(tmp_path):
    """A crash mid-storm leaves N pending admits over D digests; the
    successor's replay re-admits exactly D primaries with the
    duplicates attached as followers — one solve each, idempotent."""
    cfg = _cfg(tmp_path, journal_dir=str(tmp_path / "journal"))
    crashed = SweepService(runner_factory=stub_factory, config=cfg)
    for _ in range(3):
        crashed.submit(2.0, 8.0, 0.0)
    crashed.submit(4.0, 9.0, 0.0)
    # no start(), no stop(): the WAL holds 4 admits, zero terminals
    calls = []
    svc = SweepService(runner_factory=counting_stub_factory(calls),
                       config=cfg)
    info = svc.recover()
    assert info["replayed"] == 4
    svc.start()
    results = {seq: t.result(20.0) for seq, t in info["tickets"].items()}
    summary = svc.stop()
    assert all(r.ok for r in results.values())
    assert len({r.digest for r in results.values()}) == 2
    distinct_solved = {h for lanes in calls for h in lanes}
    assert len(distinct_solved) == 2          # D solves, not N
    # delivered followers must clear the no-silent-drop gate: a
    # recovery-coalesced duplicate counted as "lost" would trip the
    # serve_replayed_lost_count<=0 SLO rule despite zero loss
    assert summary["replayed_lost_count"] == 0
    # the next replay sees everything terminal
    assert wal.replay(cfg.journal_dir)["pending"] == []


def test_fetch_rdigest_falls_through_store_then_journal(tmp_path):
    """REGRESSION (ISSUE 12 satellite): fetch_rdigest silently missed
    once the bounded LRU evicted a digest the journal still held
    terminal — it must fall through to the store, then the journal."""
    cfg = _cfg(tmp_path, result_cache=2,
               journal_dir=str(tmp_path / "journal"))
    svc = SweepService(runner_factory=stub_factory, config=cfg)
    svc.start()
    rows = [(1.0 + i, 8.0, 0.0) for i in range(4)]
    digests = [svc.submit(*row).result(10.0).digest for row in rows]
    rd0 = wal.request_digest(*rows[0], "default")
    with svc._lock:
        assert rd0 not in svc._rdigest_index   # LRU evicted it
    got = svc.fetch_rdigest(rd0)
    assert got is not None and got.digest == digests[0]
    assert got.source == "stored"
    svc.stop()
    # journal-only service (no store): the same eviction resolves from
    # the WAL's complete records instead
    cfg2 = ServeConfig(queue_max=16, batch_cases=4, window_s=0.02,
                       result_cache=2, degrade_after=99,
                       journal_dir=str(tmp_path / "j2"))
    svc2 = SweepService(runner_factory=stub_factory, config=cfg2)
    svc2.start()
    d2 = [svc2.submit(*row).result(10.0).digest for row in rows]
    got2 = svc2.fetch_rdigest(rd0)
    assert got2 is not None and got2.digest == d2[0]
    assert got2.source == "recovered"
    svc2.stop()


def test_router_consults_local_store_before_proxying(tmp_path):
    from raft_tpu.serve.router import ReplicaRouter

    store = ResultStore(str(tmp_path))
    p = _payload()
    store.put(p)
    # one unreachable backend, never health-checked healthy: without
    # the local store every fetch would 404/503
    router = ReplicaRouter(["http://127.0.0.1:9"],
                           store_dir=str(tmp_path))
    code, body = router.result(rdigest=p["rdigest"])
    assert code == 200 and body["replica"] == "store"
    assert body["std"] == p["std"] and body["digest"] == p["digest"]
    code, body = router.result(digest=p["digest"])
    assert code == 200 and body["rdigest"] == p["rdigest"]
    code, _ = router.result(rdigest=_payload(Hs=9.9)["rdigest"])
    assert code == 404
    st = router.stats()
    assert st["store_hits"] == 2 and st["store"]["entries"] == 1


# ---------------------------------------------------------------------------
# unit: facts -> trend row -> SLO rules; bench dup shape
# ---------------------------------------------------------------------------

def test_store_facts_reach_trend_row_and_slo_rules(tmp_path):
    from raft_tpu.obs import trendstore

    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    svc.start()
    svc.submit(2.0, 8.0, 0.0).result(10.0)
    assert svc.submit(2.0, 8.0, 0.0).done()   # one hit
    summary = svc.stop()
    doc = {"schema": "raft_tpu.run_manifest/v1", "run_id": "t1",
           "kind": "serve", "status": "ok",
           "extra": {"serve": summary}}
    facts = trendstore.facts_from_manifest(doc)
    assert facts["serve_store_hits"] == 1
    assert facts["serve_store_hit_ratio"] == 0.5
    assert "serve_read_p50_ms" in facts
    assert facts["serve_warm_start_digest_mismatch"] == 0
    names = [r["name"] for r in trendstore.DEFAULT_SLO_RULES]
    assert "serve_store_corrupt_served_count" in names
    assert "serve_warm_start_digest_mismatch" in names
    rows = [{"kind": "serve", "status": "ok", "facts": facts}]
    assert trendstore.evaluate_slo(rows)["ok"]
    bad = [{"kind": "serve_storm", "status": "ok",
            "facts": {"serve_store_corrupt_served_count": 1,
                      "serve_warm_start_digest_mismatch": 2}}]
    rep = trendstore.evaluate_slo(bad)
    assert not rep["ok"]
    failing = {r["name"] for r in rep["results"] if not r["ok"]}
    assert failing == {"serve_store_corrupt_served_count",
                       "serve_warm_start_digest_mismatch"}


def test_serve_bench_dup_ratio_publishes_tier_facts(tmp_path,
                                                   monkeypatch):
    import bench

    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path / "obs"))
    report = bench.serve_bench(
        runner_factory=stub_factory, n_requests=24, rps=400.0,
        dup_ratio=0.5, store_dir=str(tmp_path / "store"),
        timeout_s=60.0)
    assert report["ok"]
    assert report["dup_ratio"] == 0.5
    assert report["store_hit_ratio"] is not None
    assert report["store_corrupt_served_count"] == 0
    assert report["warm_start_digest_mismatch"] == 0
    # the manifest row carries the tier facts for the SLO gates
    from raft_tpu.obs import trendstore
    with open(report["manifest"]) as f:
        doc = json.load(f)
    facts = trendstore.facts_from_manifest(doc)
    assert facts["serve_dup_ratio"] == 0.5
    assert facts["serve_store_corrupt_served_count"] == 0
    # store_dir=None: the scratch store is created per run and removed
    # in the finally block — repeated bench runs must not leak /tmp dirs
    import tempfile
    made = []
    real_mkdtemp = tempfile.mkdtemp
    monkeypatch.setattr(
        tempfile, "mkdtemp",
        lambda **kw: made.append(real_mkdtemp(**kw)) or made[-1])
    report = bench.serve_bench(
        runner_factory=stub_factory, n_requests=8, rps=400.0,
        dup_ratio=0.5, store_dir=None, timeout_s=60.0)
    assert report["ok"]
    assert len(made) == 1 and not os.path.exists(made[0])


# ---------------------------------------------------------------------------
# integration: warm starts on the real model + the storm acceptance
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fowt():
    from raft_tpu.serve.soak import build_fowt
    return build_fowt("Vertical_cylinder")


def _real_cfg(store_dir, **kw):
    from raft_tpu.serve.soak import default_config
    base = dict(batch_cases=2, queue_max=8, deadline_s=300.0,
                batch_deadline_s=120.0, nIter=8, tol=0.01)
    base.update(kw)
    cfg = default_config(**base)
    if store_dir is not None:
        cfg = ServeConfig(**{**cfg.__dict__, "store_dir": str(store_dir)},
                          )
    return cfg


def _solve_all(svc, rows, timeout=300.0):
    tickets = [svc.submit(*row) for row in rows]
    svc.start()
    return [t.result(timeout) for t in tickets]


def test_warm_start_parity_savings_and_poisoned_quarantine(tmp_path,
                                                           fowt):
    base_rows = [(2.0, 8.0, 0.0), (2.4, 8.4, 0.0)]
    off_rows = [(2.15, 8.1, 0.0), (2.55, 8.5, 0.0)]
    # cold reference digests for the offset cases (store-less service)
    svc = SweepService(fowt, _real_cfg(None))
    cold = _solve_all(svc, off_rows)
    svc.stop()
    assert all(r.ok for r in cold)
    cold_digests = [r.digest for r in cold]

    # seed pool: a warm-capable service cold-solves the base rows
    store_dir = tmp_path / "store"
    warm_kw = dict(warm_start=True, warm_audit_every=1, warm_radius=1.0)
    cfgw = ServeConfig(**{**_real_cfg(store_dir).__dict__, **warm_kw})
    svc = SweepService(fowt, cfgw)
    seeded = _solve_all(svc, base_rows)
    s1 = svc.stop()
    assert all(r.ok for r in seeded)
    assert ResultStore(str(store_dir)).stats()["seeds"] == 2

    # audited warm batch over the offset rows: digests BIT-FOR-BIT
    # equal to cold, seeded lanes counted, iteration savings positive
    svc = SweepService(fowt, cfgw)
    warm = _solve_all(svc, off_rows)
    s2 = svc.stop()
    assert [r.digest for r in warm] == cold_digests
    assert [r.std for r in warm] == [r.std for r in cold]
    assert s2["warm_start_seeded"] >= 2
    assert s2["warm_start_digest_mismatch"] == 0
    assert s2["warm_start_iter_savings"] > 0
    assert s2["warm_start_rejected"] == 0

    # poisoned neighbor: a FRESH store holding exactly one seed —
    # overwritten with NaNs — so the offset case must warm-start from
    # the poison.  The divergence guard rejects it, quarantines the
    # seed, falls back cold, and delivers an unchanged digest.
    pdir = tmp_path / "poison"
    cfgp = ServeConfig(**{**cfgw.__dict__, "store_dir": str(pdir),
                          "warm_audit_every": 1000})
    svc = SweepService(fowt, cfgp)
    base = _solve_all(svc, [base_rows[0]])
    svc.stop()
    assert base[0].ok
    store = ResultStore(str(pdir), keep_xi=True)
    near = store.nearest(*off_rows[0], "default", radius=1.0)[0]
    doc = store.get(near)
    nwv = len(fowt.w)
    assert store.put(doc, xi=np.full((6, nwv), np.nan, complex))
    # non-audited path (audit_every high): the guard alone must catch it
    svc = SweepService(fowt, cfgp)
    poisoned = _solve_all(svc, [off_rows[0]])
    s3 = svc.stop()
    assert poisoned[0].ok
    assert poisoned[0].digest == cold_digests[0]   # no digest deviation
    assert s3["warm_start_rejected"] >= 1
    assert s3["store_quarantined"] >= 1
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_warm_starts_total"]["series"]
    assert any(x["labels"].get("outcome") == "rejected"
               and x["value"] >= 1 for x in series)


def test_duplicate_storm_soak_acceptance(tmp_path, fowt):
    """ISSUE acceptance: N duplicate requests over D distinct digests
    solve exactly D times in one runner call; reads hit bit-for-bit
    across a restart and from a replica; corrupt@resultstore never
    serves a corrupt byte; audited warm starts save iterations at zero
    digest deviation; the storm journal replays with nothing pending."""
    from raft_tpu.serve import soak

    report = soak.run_storm(
        store_dir=str(tmp_path / "store"),
        journal_dir=str(tmp_path / "journal"),
        n_requests=12, n_distinct=4, batch_cases=4)
    assert report["ok"], json.dumps(
        {k: v for k, v in report.items() if k != "summaries"},
        indent=1, default=str)
    assert report["solves"] == 4
    assert report["coalesced"] == 8
    assert report["runner_calls_storm"] == 1
    assert report["store_corrupt_detected"] >= 4
    assert report["store_corrupt_served_count"] == 0
    assert report["warm_start_iter_savings"] > 0
    assert report["warm_start_digest_mismatch"] == 0
    assert report["journal_pending_after_storm"] == 0
