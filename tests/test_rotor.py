"""Rotor BEM + aero-servo parity vs the reference's CCBlade-generated
pickles (IEA15MW_true_calcAero-yaw_mode*.pkl).

The BEM here is an independent jax reimplementation of Ning (2014) that
reproduces CCBlade's outputs at MACHINE PRECISION: the element grid spans
[Rhub, geometry[-1][0]] like the reference (raft_rotor.py:139), the polar
pipeline replicates CCAirfoil's smoothing bivariate splines exactly, and
the hub-load integration uses CCBlade's exact per-component conventions
(see _hub_loads_one_azimuth).  The full 6-component mean load vector is
regression-checked across the 30-case (speed x heading) envelope in
test_hub_loads_full_envelope_parity at 1e-8.
"""
import os
import pickle

import numpy as np
import pytest
import yaml
from numpy.testing import assert_allclose

from raft_tpu.models import rotor as R

YAML = "/root/reference/tests/test_data/IEA15MW.yaml"


@pytest.fixture(scope="module")
def rotor_and_truth():
    if not os.path.isfile(YAML):
        pytest.skip("reference test data not available")
    d = yaml.safe_load(open(YAML))
    t = d["turbine"]
    t["nrotors"] = 1
    t["rho_air"] = d["site"].get("rho_air", 1.225)
    t["mu_air"] = d["site"].get("mu_air", 1.81e-5)
    t["shearExp_air"] = d["site"].get("shearExp_air", 0.12)
    t["rho_water"], t["mu_water"], t["shearExp_water"] = 1025.0, 1e-3, 0.12
    s = d["settings"]
    w = np.arange(s["min_freq"], s["max_freq"] + 0.5 * s["min_freq"],
                  s["min_freq"]) * 2 * np.pi
    rot = R.build_rotor(t, w, 0)
    truth = pickle.load(open(YAML.replace(
        ".yaml", "_true_calcAero-yaw_mode0.pkl"), "rb"))
    return rot, w, truth


def test_thrust_torque_parity(rotor_and_truth):
    """T/Q vs CCBlade across wind speeds, aligned inflow."""
    rot, w, truth = rotor_and_truth
    pose = R.rotor_pose(rot)
    Rq = np.asarray(pose["R_q"])
    # truth cases are ordered ws x heading x TI; heading=0, TI=0 is index 4
    # within each block of 10
    for blk, U in enumerate([5.0, 10.0, 10.59, 15.0, 20.0, 25.0]):
        tv = truth[blk * 10 + 4]
        assert tv["case"]["wind_heading"] == 0
        ref_F = Rq.T @ tv["f_aero0"][:3]
        ref_M = Rq.T @ tv["f_aero0"][3:]
        Om = float(np.interp(U, rot.Uhub_ops, rot.Omega_rpm_ops))
        pi_ = float(np.interp(U, rot.Uhub_ops, rot.pitch_deg_ops))
        out = R.bem_evaluate(rot, U, Om, pi_, tilt=-float(rot.shaft_tilt),
                             yaw=0.0)
        assert_allclose(float(out["T"]), ref_F[0], rtol=1e-8)
        assert_allclose(float(out["Q"]), ref_M[1], rtol=1e-8)


def test_thrust_derivative_parity(rotor_and_truth):
    """dT/dU (extracted from the reference's b_aero trace): the autodiff
    Jacobian vs CCBlade's analytic derivatives."""
    rot, w, truth = rotor_and_truth
    for blk, U in enumerate([5.0, 10.0, 15.0, 25.0]):
        idx = [5.0, 10.0, 10.59, 15.0, 20.0, 25.0].index(U) * 10 + 4
        tv = truth[idx]
        ref_dTdU = np.trace(tv["b_aero"][:3, :3, 0])
        _, J = R.bem_thrust_torque_derivs(rot, U,
                                          float(np.interp(U, rot.Uhub_ops, rot.Omega_rpm_ops)),
                                          float(np.interp(U, rot.Uhub_ops, rot.pitch_deg_ops)),
                                          tilt=-float(rot.shaft_tilt), yaw=0.0)
        assert_allclose(float(J[0, 0]), ref_dTdU, rtol=1e-5)


def test_calc_aero_structure(rotor_and_truth):
    """calc_aero end-to-end: shapes, rotation structure, and f/b consistency
    with dT/dU for aeroServoMod=1."""
    rot, w, truth = rotor_and_truth
    tv = truth[14]  # ws=10, heading=0, TI=0
    out = R.calc_aero(rot, w, tv["case"])
    f0 = np.asarray(out["f0"])
    assert f0.shape == (6,)
    assert_allclose(f0[0], tv["f_aero0"][0], rtol=1e-8)
    assert_allclose(f0[4], tv["f_aero0"][4], rtol=1e-8)  # pitch moment
    b = np.asarray(out["b"])
    assert b.shape == (6, 6, len(w))
    # damping trace equals dT/dU at every frequency (freq-independent for mod 1)
    assert_allclose(np.trace(b[:3, :3, 0]), float(out["derivs"]["dT_dU"]), rtol=1e-9)
    # zero-turbulence: no excitation
    assert np.allclose(np.asarray(out["f"]), 0.0)


def test_calc_aero_excitation_turbulent(rotor_and_truth):
    """With TI=0.5 the excitation spectrum f_aero is dT_dU * sqrt(S_rot)
    rotated; compare to the reference at low frequency where the
    reference's scipy Struve-Bessel difference is still accurate."""
    rot, w, truth = rotor_and_truth
    tv = truth[15]  # ws=10, heading=0, TI=0.5
    out = R.calc_aero(rot, w, tv["case"])
    ours = np.asarray(out["f"])
    ref = tv["f_aero"]
    # low-frequency bins: 2*R*kappa < ~18 keeps scipy's difference accurate
    f_hz = w / (2 * np.pi)
    kappa = 12 * np.sqrt((f_hz / 10.0) ** 2 + (0.12 / (8.1 * 42)) ** 2)
    sel = 2 * rot.R_rot * kappa < 18.0
    assert sel.sum() >= 2
    assert_allclose(np.abs(ours[0, sel]), np.abs(ref[0, sel]), rtol=0.03)


def test_kaimal_spectrum_positive(rotor_and_truth):
    rot, w, _ = rotor_and_truth
    U, V, W, Rot = R.kaimal_spectra(w, 10.0, 150.0, rot.R_rot, 1.8)
    for arr in (U, V, W, Rot):
        a = np.asarray(arr)
        assert np.all(np.isfinite(a)) and np.all(a >= 0)
    # rotor averaging attenuates relative to point spectrum at high freq
    assert float(Rot[-1]) < float(U[-1])


def test_bem_derivatives_match_fd(rotor_and_truth):
    """AD derivatives vs finite differences of our own evaluate."""
    rot, w, _ = rotor_and_truth
    U, Om, pi_ = 10.0, 7.16, -0.25
    TQ, J = R.bem_thrust_torque_derivs(rot, U, Om, pi_, tilt=0.1, yaw=0.05)
    eps = 1e-4
    for j, (dp, dm) in enumerate([((U + eps, Om, pi_), (U - eps, Om, pi_)),
                                  ((U, Om + eps, pi_), (U, Om - eps, pi_)),
                                  ((U, Om, pi_ + eps), (U, Om, pi_ - eps))]):
        op = R.bem_evaluate(rot, *dp, tilt=0.1, yaw=0.05)
        om_ = R.bem_evaluate(rot, *dm, tilt=0.1, yaw=0.05)
        fd_T = (float(op["T"]) - float(om_["T"])) / (2 * eps)
        fd_Q = (float(op["Q"]) - float(om_["Q"])) / (2 * eps)
        assert_allclose(float(J[0, j]), fd_T, rtol=2e-3, atol=1.0)
        assert_allclose(float(J[1, j]), fd_Q, rtol=2e-3, atol=10.0)


def test_hub_loads_full_envelope_parity(rotor_and_truth):
    """Full 6-DOF mean aero load vector vs the reference across the whole
    yaw_mode-0 pickle grid (6 speeds x 5 headings x 2 TI): per-case error
    normalized by the largest force/moment component: machine-precision
    parity (the solve tolerance of the bisection/Newton phi iteration is
    the only difference vs CCBlade's brentq)."""
    rot, w, truth = rotor_and_truth
    errs = []
    # mean loads are TI-independent: the TI=0 half covers the f0 envelope.
    # bem_evaluate + R_q reproduces calc_aero's f0 assembly (rotor.py:727)
    # without the Jacobian/spectral work the comparison doesn't use.
    for tv in truth:
        c = tv["case"]
        if float(c.get("turbulence", 0)) != 0:
            continue
        pose = R.rotor_pose(rot, None,
                            inflow_heading=np.radians(float(c["wind_heading"])),
                            yaw_command=np.radians(float(c.get("yaw_misalign", 0))))
        q = np.asarray(pose["q"])
        Rq = np.asarray(pose["R_q"])
        yawmis = np.arctan2(q[1], q[0]) - np.radians(float(c["wind_heading"]))
        tilt = np.arctan2(q[2], np.hypot(q[0], q[1]))
        U = float(c["wind_speed"])
        Om = float(np.interp(U, rot.Uhub_ops, rot.Omega_rpm_ops))
        pi_ = float(np.interp(U, rot.Uhub_ops, rot.pitch_deg_ops))
        o = R.bem_evaluate(rot, U, Om, pi_, tilt=tilt, yaw=yawmis)
        f0 = np.concatenate([
            Rq @ [float(o["T"]), float(o["Y"]), float(o["Z"])],
            Rq @ [float(o["My"]), float(o["Q"]), float(o["Mz"])]])
        ref = np.asarray(tv["f_aero0"])
        sF = np.abs(ref[:3]).max()
        sM = np.abs(ref[3:]).max()
        errs.append(max(np.abs(f0[:3] - ref[:3]).max() / sF,
                        np.abs(f0[3:] - ref[3:]).max() / sM))
    errs = np.asarray(errs)
    assert np.median(errs) < 1e-9, np.median(errs)
    assert errs.max() < 1e-7, errs.max()


def test_yaw_misalign_applied_unlike_reference(rotor_and_truth):
    """Documents a deliberate deviation: the reference's calcAero never
    consumes case['yaw_misalign'] — raft_rotor.py:815 calls setYaw() with
    no argument, so the yaw command stays 0 and its yaw_mode-2/3 pickles
    are exactly yaw-invariant (verified here from the data).  This
    framework wires the case yaw command through rotor_pose into the BEM,
    so thrust genuinely drops with misalignment (~cos^2 scale)."""
    rot, w, truth = rotor_and_truth
    p = "/root/reference/tests/test_data/IEA15MW_true_calcAero-yaw_mode2.pkl"
    t2 = pickle.load(open(p, "rb"))
    rows = {}
    for tv in t2:
        c = tv["case"]
        if (c["wind_speed"] == 10.0 and c["wind_heading"] == 0
                and c.get("turbulence") == 0):
            rows[float(c["yaw_misalign"])] = np.asarray(tv["f_aero0"])
    # the reference ground truth ignores the yaw command entirely
    assert_allclose(rows[45.0], rows[0.0], rtol=1e-12)
    assert_allclose(rows[-90.0], rows[0.0], rtol=1e-12)

    # ours: thrust falls with misalignment, roughly cos^2
    U = 10.0
    Om = float(np.interp(U, rot.Uhub_ops, rot.Omega_rpm_ops))
    pi_ = float(np.interp(U, rot.Uhub_ops, rot.pitch_deg_ops))
    T0 = float(R.bem_evaluate(rot, U, Om, pi_, tilt=0.0, yaw=0.0)["T"])
    T45 = float(R.bem_evaluate(rot, U, Om, pi_, tilt=0.0,
                               yaw=np.radians(45.0))["T"])
    assert 0.3 * T0 < T45 < 0.75 * T0


@pytest.fixture(scope="module")
def servo_rotor():
    """IEA15MW rotor with aeroServoMod=2 control (gains from the
    VolturnUS-S test yaml, which carries the ROSCO pitch/torque tables
    for the same turbine)."""
    vol = "/root/reference/tests/test_data/VolturnUS-S.yaml"
    if not (os.path.isfile(YAML) and os.path.isfile(vol)):
        pytest.skip("reference test data not available")
    d = yaml.safe_load(open(YAML))
    dv = yaml.safe_load(open(vol))
    t = d["turbine"]
    t["nrotors"] = 1
    t["aeroServoMod"] = 2
    t["pitch_control"] = dv["turbine"]["pitch_control"]
    t["torque_control"] = dv["turbine"]["torque_control"]
    t["gear_ratio"] = dv["turbine"].get("gear_ratio", 1.0)
    t["I_drivetrain"] = dv["turbine"]["I_drivetrain"]
    t["rho_air"] = d["site"].get("rho_air", 1.225)
    t["mu_air"] = d["site"].get("mu_air", 1.81e-5)
    t["shearExp_air"] = d["site"].get("shearExp_air", 0.12)
    t["rho_water"], t["mu_water"], t["shearExp_water"] = 1025.0, 1e-3, 0.12
    w = np.arange(0.01, 1.0 + 0.005, 0.01) * 2 * np.pi
    return R.build_rotor(t, w, 0), w


def test_hqt_per_term_decomposition(servo_rotor):
    """Per-term parity of the aeroServoMod-2 closed-loop assembly against
    an INDEPENDENT transcription of the reference formulas
    (raft_rotor.py:884-961: D denominator :906, control transfer C :909,
    H_QT :943-945, excitation f2 :948, damping b2 :949, added mass a2
    :950) evaluated from the same derivative values, at operating points
    spanning below-rated, rated, and above-rated.  Pins the closed-loop
    algebra so a transcription drift cannot hide inside end-to-end
    regressions (VERDICT r4 item 7)."""
    rot, w = servo_rotor
    for U in (6.0, 9.0, 10.59, 12.0, 16.0, 24.0):
        case = {"wind_speed": U, "wind_heading": 0.0, "turbulence": 0.1,
                "turbine_status": "operating", "yaw_misalign": 0.0}
        out = R.calc_aero(rot, w, case)
        dv = out["derivs"]
        dT_dU, dT_dOm, dT_dPi = (float(dv["dT_dU"]), float(dv["dT_dOm"]),
                                 float(dv["dT_dPi"]))
        dQ_dU, dQ_dOm, dQ_dPi = (float(dv["dQ_dU"]), float(dv["dQ_dOm"]),
                                 float(dv["dQ_dPi"]))
        # gain scheduling exactly as the reference (flipped-sign ROSCO,
        # torque gains only active when the pitch gains are parked)
        kp_beta = -np.interp(U, rot.Uhub_ops, rot.kp_0)
        ki_beta = -np.interp(U, rot.Uhub_ops, rot.ki_0)
        kp_tau = rot.kp_tau * (kp_beta == 0)
        ki_tau = rot.ki_tau * (ki_beta == 0)
        # the pitch-speed crossover must actually be exercised on both
        # sides of rated for the term test to mean anything
        if U <= 9.0:
            assert kp_beta == 0 and kp_tau != 0
        if U >= 12.0:
            assert kp_beta != 0 and kp_tau == 0

        # --- independent transcription of the reference formulas ---
        D = (rot.I_drivetrain * w**2
             + (dQ_dOm + kp_beta * dQ_dPi - rot.Ng * kp_tau) * 1j * w
             + ki_beta * dQ_dPi - rot.Ng * ki_tau)
        C_ref = 1j * w * (dQ_dU - rot.k_float * dQ_dPi
                          / float(np.asarray(out["pose"]["r_hub"])[2])) / D
        H_QT = ((dT_dOm + kp_beta * dT_dPi) * 1j * w + ki_beta * dT_dPi) / D
        T_cplx = (dT_dU - rot.k_float * dT_dPi
                  - H_QT * (dQ_dU - rot.k_float * dQ_dPi))
        b2 = np.real(T_cplx)
        a2 = np.real(T_cplx / (1j * w))
        V_w = np.asarray(out["V_w"])
        f2 = (dT_dU - H_QT * dQ_dU) * V_w

        # control transfer function exposed for the omega/torque/bPitch
        # output channels
        assert_allclose(np.asarray(out["C"]), C_ref, rtol=1e-10)
        # head-on, zero tilt command: R_q is the shaft rotation only; the
        # fore-aft (0,0) entry carries cos^2(tilt) of the axis transform
        Rq = np.asarray(out["pose"]["R_q"])
        a = np.asarray(out["a"])
        b = np.asarray(out["b"])
        f = np.asarray(out["f"])
        # direct reconstruction: a/b blocks are R_q @ diag(x,0,0) @ R_q^T
        e1 = np.zeros((3, 3)); e1[0, 0] = 1.0
        for arr, x in ((a, a2), (b, b2)):
            expect = np.einsum("ab,w,bc->acw", Rq @ e1, x, Rq.T)
            assert_allclose(arr[:3, :3, :], expect, rtol=1e-9,
                            atol=1e-9 * np.abs(expect).max())
            assert np.all(arr[3:, :, :] == 0) and np.all(arr[:, 3:, :] == 0)
        expect_f = np.einsum("ab,bw->aw",
                             Rq.astype(complex),
                             np.stack([f2, np.zeros_like(f2),
                                       np.zeros_like(f2)]))
        assert_allclose(f[:3, :], expect_f, rtol=1e-9,
                        atol=1e-9 * np.abs(expect_f).max())
