"""The resilient always-on sweep service (raft_tpu/serve).

Unit tier (stub batch engines, no solves): admission control +
Retry-After hints, the retry matrix and deterministic backoff, the
watchdog abandon -> solo re-admit -> quarantine path, the service
degradation ladder, and the serve run manifest / trend-store row.

Integration tier (one coarse Vertical_cylinder model): the warm batch
runner's parity with the plain batched solver and its executable-cache
round trip, and the ISSUE acceptance scenario — the deterministic chaos
soak (``serve.soak``): NaN poisoning, a one-shot kernel raise, cache
corruption, an injected hang through the watchdog, and an admission
burst, with zero unhandled errors and every completed request
digest-identical to the clean pass.
"""
import os
import time

import numpy as np
import pytest

from raft_tpu import errors, obs
from raft_tpu.serve import (DEFAULT_BUDGETS, TERMINAL, RetryPolicy,
                            ServeConfig, SweepService, Watchdog)
from raft_tpu.testing import faults


def stub_factory(mode, fowt, ncases, **kw):
    """Deterministic instant batch engine: std row = Hs replicated."""
    def run(Hs, Tp, beta):
        Hs = np.asarray(Hs)
        return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                "iters": np.full(len(Hs), 3),
                "converged": np.ones(len(Hs), bool)}
    run.ncases = ncases
    run.cache_state = "stub"
    return run


def _cfg(**kw):
    base = dict(queue_max=8, batch_cases=2, window_s=0.02,
                batch_deadline_s=5.0, retry_base_s=0.01,
                degrade_after=99)
    base.update(kw)
    return ServeConfig(**base)


# ---------------------------------------------------------------------------
# unit: config, retry policy, fault grammar, watchdog
# ---------------------------------------------------------------------------

def test_config_validation_is_typed():
    with pytest.raises(errors.ModelConfigError) as exc:
        ServeConfig(queue_max=0, window_s=-1.0)
    assert "queue_max" in str(exc.value) and "window_s" in str(exc.value)


def test_retry_policy_matrix():
    p = RetryPolicy(seed=7)
    assert p.classify(errors.KernelFailure("x")) == "KernelFailure"
    # MRO walk: a taxonomy subclass inherits its parent's policy
    class SubKernel(errors.KernelFailure):
        pass
    assert p.classify(SubKernel("x")) == "KernelFailure"
    assert p.budget(errors.KernelFailure("x")) == \
        DEFAULT_BUDGETS["KernelFailure"]
    for name in TERMINAL:
        assert p.budget(getattr(errors, name)("x")) == 0
    # non-taxonomy errors are bugs, not transients
    assert p.budget(RuntimeError("x")) == 0
    assert p.should_retry(errors.NonFiniteResult("x"), 1)
    assert not p.should_retry(errors.NonFiniteResult("x"), 2)


def test_retry_backoff_deterministic_and_bounded():
    p = RetryPolicy(base_s=0.05, cap_s=2.0, jitter=0.5, seed=3)
    seq = [p.backoff_s("reqA", i) for i in range(8)]
    assert seq == [p.backoff_s("reqA", i) for i in range(8)]  # repeatable
    for i, d in enumerate(seq):
        raw = min(2.0, 0.05 * 2 ** i)
        assert raw * 0.5 <= d <= raw          # jitter in [1-j, 1]
    assert p.backoff_s("reqA", 0) != p.backoff_s("reqB", 0)  # decorrelated
    assert RetryPolicy(jitter=0.0).backoff_s("x", 3) == 0.05 * 8
    m = p.matrix()
    assert m["ModelConfigError"]["terminal"] is True
    assert m["KernelFailure"]["budget"] == 3


def test_faults_serve_grammar():
    specs = faults.parse("hang@serve:req=5:ms=400,hang@serve:s=2,"
                         "raise@serve:once,nan@serve,hang@dynamics")
    assert [f["action"] for f in specs] == ["hang", "hang", "raise"]
    assert specs[0]["hang_s"] == pytest.approx(0.4)
    assert specs[0]["match"] == {"req": 5}
    assert specs[1]["hang_s"] == pytest.approx(2.0)
    faults.install("hang@serve:req=1:ms=50")
    try:
        assert faults.fire_info("serve", req=0) is None
        f = faults.fire_info("serve", req=1)
        assert f["action"] == "hang" and f["hang_s"] == pytest.approx(0.05)
    finally:
        faults.clear()


def test_watchdog_arm_disarm_race_contract():
    fired = []
    wd = Watchdog(tick_s=0.01)
    wd.start()
    try:
        wid = wd.arm(time.monotonic() + 10.0, lambda: fired.append("no"))
        assert wd.disarm(wid) is True          # not expired: caller owns
        wid = wd.arm(time.monotonic() + 0.03, lambda: fired.append("yes"))
        deadline = time.monotonic() + 2.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fired == ["yes"]
        assert wd.disarm(wid) is False         # expired: caller lost
        assert wd.armed_count() == 0
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# unit: admission control
# ---------------------------------------------------------------------------

def test_admission_queue_full_rejects_with_retry_after():
    svc = SweepService(runner_factory=stub_factory, config=_cfg(
        queue_max=3))
    for i in range(3):                        # fill pre-start: worker idle
        svc.submit(1.0 + i, 8.0, 0.0)
    with pytest.raises(errors.AdmissionRejected) as exc:
        svc.submit(9.0, 8.0, 0.0)
    e = exc.value
    assert e.ctx["reason"] == "queue_full"
    assert e.retry_after_s > 0.0
    assert e.context()["retry_after_s"] == e.retry_after_s
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_admission_rejects_total"]["series"]
    assert any(s["labels"] == {"reason": "queue_full"} for s in series)
    svc.start()
    assert svc.stop()["completed"] == 3


def test_admission_deadline_pressure_rejects():
    svc = SweepService(runner_factory=stub_factory, config=_cfg())
    with pytest.raises(errors.AdmissionRejected) as exc:
        # the estimated queue wait (>= one batch cadence) cannot meet
        # a 100 us deadline — shed instead of admitting a doomed request
        svc.submit(1.0, 8.0, 0.0, deadline_s=1e-4)
    assert exc.value.ctx["reason"] == "deadline_pressure"
    svc.start()
    assert svc.stop()["rejected"] == 1


def test_admission_rejected_is_terminal_for_retry():
    assert RetryPolicy().budget(
        errors.AdmissionRejected("x", retry_after_s=1.0)) == 0


# ---------------------------------------------------------------------------
# unit: the happy path + async delivery
# ---------------------------------------------------------------------------

def test_stub_service_completes_and_delivers_by_digest():
    svc = SweepService(runner_factory=stub_factory, config=_cfg())
    svc.start()
    tickets = [svc.submit(1.0 + i, 8.0, 0.0) for i in range(5)]
    results = [t.result(10.0) for t in tickets]
    assert all(r.ok for r in results)
    assert [r.seq for r in results] == list(range(5))
    # ledger-digest-keyed async delivery
    for r in results:
        assert r.digest.startswith("sha256:")
        assert svc.fetch(r.digest).request_id == r.request_id
    # the digest is EXACTLY the ledger entry digest of the same metrics
    from raft_tpu.obs.ledger import digest_metrics
    r = results[2]
    assert r.digest == digest_metrics(
        {"std": np.asarray(r.std), "iters": r.iters,
         "converged": r.converged})
    summary = svc.stop()
    assert summary["completed"] == 5 and summary["failed"] == 0
    assert summary["p50_latency_s"] is not None


# ---------------------------------------------------------------------------
# unit: watchdog abandon -> solo re-admit -> quarantine
# ---------------------------------------------------------------------------

def test_watchdog_abandons_hang_quarantines_offender_readmits_rest():
    faults.install("hang@serve:req=1:ms=600")
    try:
        cfg = _cfg(batch_deadline_s=0.25, watchdog_tick_s=0.02,
                   hang_quarantine_after=2)
        svc = SweepService(runner_factory=stub_factory, config=cfg)
        svc.start()
        t0 = svc.submit(1.0, 8.0, 0.0)
        t1 = svc.submit(2.0, 8.0, 0.0)        # seq 1 carries the hang
        r0 = t0.result(20.0)
        r1 = t1.result(20.0)
    finally:
        faults.clear()
    # the survivor was re-admitted solo and completed normally
    assert r0.ok and np.allclose(r0.std, 1.0)
    # the offender hung again solo -> second strike -> typed quarantine
    assert not r1.ok and r1.quarantined
    assert r1.error["error"] == "DeadlineExceeded"
    summary = svc.stop()
    assert summary["abandoned_batches"] == 2       # batch, then solo
    assert summary["deadline_misses"] == 3         # 2 members + 1 solo
    assert summary["quarantined"] == 1
    assert summary["unhandled"] == 0
    snap = obs.snapshot()
    assert snap["raft_tpu_serve_deadline_misses_total"][
        "series"][0]["value"] == 3.0


# ---------------------------------------------------------------------------
# unit: retry/backoff over typed batch failures
# ---------------------------------------------------------------------------

def test_transient_batch_failure_retried_within_budget():
    calls = {"n": 0}

    def flaky(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            calls["n"] += 1
            if calls["n"] == 1:
                raise errors.KernelFailure("transient", injected=True)
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    svc = SweepService(runner_factory=flaky, config=_cfg())
    svc.start()
    tickets = [svc.submit(1.0 + i, 8.0, 0.0) for i in range(2)]
    results = [t.result(10.0) for t in tickets]
    summary = svc.stop()
    assert all(r.ok and r.attempts == 1 for r in results)
    assert summary["retries"] == 2
    assert summary["retried_recovered"] == 2


def test_terminal_failure_not_retried():
    def broken(mode, fowt, ncases, **kw):
        def run(Hs, Tp, beta):
            raise errors.ModelConfigError("bad model", mode=mode)
        run.ncases = ncases
        return run

    svc = SweepService(runner_factory=broken, config=_cfg())
    svc.start()
    r = svc.submit(1.0, 8.0, 0.0).result(10.0)
    summary = svc.stop()
    assert not r.ok and r.error["error"] == "ModelConfigError"
    assert r.attempts == 0 and summary["retries"] == 0


def test_persistent_lane_poison_exhausts_budget_as_typed_failure():
    faults.install("nan@dynamics:case=1")
    try:
        svc = SweepService(runner_factory=stub_factory, config=_cfg())
        svc.start()
        t0 = svc.submit(1.0, 8.0, 0.0)
        t1 = svc.submit(2.0, 8.0, 0.0)        # seq 1 poisoned every pass
        r0 = t0.result(20.0)
        r1 = t1.result(20.0)
        summary = svc.stop()
    finally:
        faults.clear()
    assert r0.ok
    assert not r1.ok and r1.error["error"] == "NonFiniteResult"
    assert r1.attempts == DEFAULT_BUDGETS["NonFiniteResult"]
    assert summary["unhandled"] == 0


def test_unhandled_bug_becomes_typed_result_service_survives():
    calls = {"n": 0}

    def buggy(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ZeroDivisionError("bug, not a transient")
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    svc = SweepService(runner_factory=buggy, config=_cfg())
    svc.start()
    r1 = svc.submit(1.0, 8.0, 0.0).result(10.0)
    r2 = svc.submit(2.0, 8.0, 0.0).result(10.0)   # service still alive
    summary = svc.stop()
    assert not r1.ok and r1.error["error"] == "KernelFailure"
    assert r2.ok
    assert summary["unhandled"] == 1


# ---------------------------------------------------------------------------
# unit: degradation ladder
# ---------------------------------------------------------------------------

def test_ladder_degrades_on_sustained_violation_and_recovers():
    delays = {"n": 0}

    def paced(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            delays["n"] += 1
            if delays["n"] <= 2:
                time.sleep(0.08)              # the two violating batches
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    cfg = _cfg(batch_cases=1, window_s=0.0, latency_slo_s=0.05,
               degrade_after=2, upgrade_after=2)
    svc = SweepService(runner_factory=paced, config=cfg,
                       degraded_fowts={"no_qtf": object()})
    assert svc.ladder == ("full", "no_qtf", "reject")
    svc.start()
    results = [svc.submit(1.0 + i, 8.0, 0.0).result(10.0)
               for i in range(6)]
    summary = svc.stop()
    assert all(r.ok for r in results)
    trans = [(t["from"], t["to"], t["reason"])
             for t in summary["mode_transitions"]]
    assert ("full", "no_qtf", "slo_violation") in trans
    assert ("no_qtf", "full", "healthy") in trans
    assert results[-1].mode == "full"         # recovered by the end
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_mode_transitions_total"]["series"]
    assert any(s["labels"] == {"from": "full", "to": "no_qtf"}
               for s in series)


def test_reject_mode_sheds_then_exits_after_hold():
    def instant(mode, fowt, ncases, **kw):
        return stub_factory(mode, fowt, ncases, **kw)

    cfg = _cfg(batch_cases=1, window_s=0.0, latency_slo_s=0.0,
               degrade_after=1, upgrade_after=99, reject_hold_s=0.2)
    svc = SweepService(runner_factory=instant, config=cfg)
    assert svc.ladder == ("full", "reject")   # no degraded models
    svc.start()
    first = svc.submit(1.0, 8.0, 0.0)
    assert first.result(10.0).ok              # latency_slo 0 -> violation
    deadline = time.monotonic() + 5.0
    while svc.mode != "reject" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert svc.mode == "reject"
    with pytest.raises(errors.AdmissionRejected) as exc:
        svc.submit(2.0, 8.0, 0.0)
    assert exc.value.ctx["reason"] == "degraded"
    # the hold elapses with an empty queue -> the service probes back up
    deadline = time.monotonic() + 5.0
    while svc.mode == "reject" and time.monotonic() < deadline:
        time.sleep(0.02)
    assert svc.mode == "full"
    svc.submit(3.0, 8.0, 0.0).result(10.0)
    svc.stop()


# ---------------------------------------------------------------------------
# unit: serve manifest -> trend store row -> SLO rules
# ---------------------------------------------------------------------------

def test_serve_manifest_and_trend_row(tmp_path, monkeypatch):
    from raft_tpu.obs import trendstore as T

    monkeypatch.setenv("RAFT_TPU_TREND_DB", str(tmp_path / "t.sqlite"))
    obs.configure(str(tmp_path))
    svc = SweepService(runner_factory=stub_factory, config=_cfg())
    svc.start()
    run_id = svc._manifest.run_id
    svc.submit(1.0, 8.0, 0.0).result(10.0)
    summary = svc.stop()
    assert summary["completed"] == 1
    # manifest written with the serve facts + retry matrix
    path = tmp_path / f"serve_{run_id}.manifest.json"
    assert path.is_file()
    import json
    doc = json.loads(path.read_text())
    assert doc["status"] == "ok" and doc["kind"] == "serve"
    assert doc["extra"]["serve"]["completed"] == 1
    assert doc["extra"]["retry_matrix"]["ModelConfigError"]["terminal"]
    # flight-recorder stream exists and carries the service lifecycle
    from raft_tpu.obs import events as E
    evs = E.read(str(tmp_path / f"serve_{run_id}.events.jsonl"))
    types = {e["type"] for e in evs}
    assert {"begin", "service_start", "request_done", "end"} <= types
    # trend row + the serve SLO rules over it
    store = T.TrendStore(str(tmp_path / "t.sqlite"))
    rows = store.rows(kind="serve")
    assert rows and rows[0]["facts"]["serve_completed"] == 1
    report = T.evaluate_slo(rows)
    assert report["ok"]
    by_name = {r["name"]: r for r in report["results"]}
    assert not by_name["serve_unhandled_errors"]["skipped"]
    assert by_name["serve_retry_success_ratio"]["skipped"]  # no retries


# ---------------------------------------------------------------------------
# integration: warm batch runner + the chaos soak (coarse cylinder)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cyl_fowt():
    from raft_tpu.io.designs import load_design
    from raft_tpu.models.fowt import build_fowt

    design = load_design("Vertical_cylinder")
    w = np.arange(0.05, 0.5, 0.05) * 2 * np.pi
    return build_fowt(design, w,
                      depth=float(design["site"]["water_depth"]))


def test_model_make_service_builds_coarse_rung():
    from raft_tpu.io.designs import load_design
    from raft_tpu.model import Model

    design = load_design("Vertical_cylinder")
    design.setdefault("settings", {})
    design["settings"].update({"min_freq": 0.05, "max_freq": 0.5})
    model = Model(design)
    svc = model.make_service(batch_cases=2, queue_max=4)
    assert svc.ladder == ("full", "coarse", "reject")
    assert len(svc._fowts["coarse"].w) == (len(model.w) + 1) // 2
    assert svc.cfg.batch_cases == 2
    # not started: nothing to stop, but stop() must be a clean no-op
    assert svc.stop()["completed"] == 0


def test_batch_runner_matches_batched_solver(cyl_fowt, tmp_path,
                                             monkeypatch):
    import jax

    from raft_tpu.parallel import exec_cache
    from raft_tpu.parallel.sweep import make_batch_runner, make_case_solver

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path))
    exec_cache.reset_memo()
    Hs = np.array([1.5, 2.5, 3.5])
    Tp = np.array([8.0, 9.0, 10.0])
    beta = np.array([0.0, 0.5, 1.0])
    runner = make_batch_runner(cyl_fowt, 3, nIter=4)
    assert runner.cache_state == "miss"
    out = runner(Hs, Tp, beta)
    ref = jax.jit(make_case_solver(cyl_fowt, nIter=4).batched)(
        Hs, Tp, beta)
    np.testing.assert_array_equal(np.asarray(out["std"]),
                                  np.asarray(ref["std"]))
    np.testing.assert_array_equal(np.asarray(out["iters"]),
                                  np.asarray(ref["iters"]))
    # second build: a warm start through the executable cache (served
    # from the in-process memo without re-reading disk), same numbers
    runner2 = make_batch_runner(cyl_fowt, 3, nIter=4)
    assert runner2.cache_state == "hit"
    out2 = runner2(Hs, Tp, beta)
    np.testing.assert_array_equal(np.asarray(out2["std"]),
                                  np.asarray(out["std"]))


def test_chaos_soak_deterministic(cyl_fowt, tmp_path, monkeypatch):
    """ISSUE acceptance: the deterministic chaos soak — injected NaNs,
    a one-shot kernel raise, cache corruption, a hang through the
    watchdog, and an admission burst; zero unhandled errors, bounded
    queue, typed failures only, and every completed request
    digest-identical to the clean pass."""
    from raft_tpu.parallel import exec_cache
    from raft_tpu.serve import soak

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR",
                       str(tmp_path / "cache"))
    exec_cache.reset_memo()
    report = soak.run_soak(cyl_fowt, n_requests=12)
    assert report["ok"], report
    assert report["digest_mismatches"] == []
    # the admission burst overflowed the queue_max=8 watermark exactly
    assert report["burst_rejected"] == 4
    chaos = report["chaos"]
    assert chaos["unhandled"] == 0
    assert chaos["admitted"] == 12
    # seq 2: persistently poisoned -> retried to budget -> typed
    # failure.  Its total attempts are 3: one KernelFailure retry (it
    # rode the first batch, which hit the one-shot kernel raise) plus
    # its full per-class NonFiniteResult budget — the budgets are
    # per-error-class, so the kernel hiccup does not eat into them.
    f2 = report["failures"][2]
    assert f2["error"] == "NonFiniteResult" and not f2["quarantined"]
    assert f2["attempts"] == 1 + DEFAULT_BUDGETS["NonFiniteResult"]
    # seq 5: hung twice (batch, then solo) -> watchdog quarantine
    f5 = report["failures"][5]
    assert f5["error"] == "DeadlineExceeded" and f5["quarantined"]
    assert set(report["failures"]) == {2, 5}
    assert report["completed"] == 10
    # the one-shot kernel failure was retried and recovered
    assert chaos["retries"] >= 4 and chaos["retried_recovered"] >= 3
    # hang path: 4 batch members + 1 solo re-run missed the deadline
    assert chaos["deadline_misses"] == 5
    assert chaos["abandoned_batches"] == 2
    # the parity phase must not have served degraded physics
    assert chaos["mode"] == "full" and chaos["n_mode_transitions"] == 0
