"""Durable serving (raft_tpu/serve journal + tenancy + handoff).

Unit tier (stub batch engines, no solves): the shared crash-safe JSONL
codec (obs/journalio), the kill/torn fault grammar, write-ahead journal
record schema + replay classification, the ISSUE replay-idempotency
matrix (completed digest / duplicate submission / accepted-unfinished /
torn tail), WAL-before-ack ordering, seq preservation across recovery,
graceful drain/handoff, and the multi-tenant warm-runner registry with
LRU eviction.

Integration tier (one coarse Vertical_cylinder model, subprocess): the
ISSUE kill-restart acceptance — a journaled child service hard-killed
mid-batch by ``kill@serve``, restarted via ``SweepService.recover()``
on the same journal dir, with zero accepted requests lost, digests
identical to an uninterrupted clean run, and a span-asserted warm start
from the executable cache.
"""
import json
import os
import time

import numpy as np
import pytest

from raft_tpu import errors, obs
from raft_tpu.obs import journalio
from raft_tpu.serve import ServeConfig, SweepService, Tenant
from raft_tpu.serve import journal as wal
from raft_tpu.serve.tenancy import TenantRegistry
from raft_tpu.testing import faults


def stub_factory(mode, fowt, ncases, **kw):
    """Deterministic instant batch engine: std row = Hs replicated
    (+ the tenant fowt's marker offset when one is handed in)."""
    offset = float(getattr(fowt, "marker", 0.0) or 0.0)

    def run(Hs, Tp, beta):
        Hs = np.asarray(Hs)
        return {"std": np.stack([np.full(6, float(h) + offset)
                                 for h in Hs]),
                "iters": np.full(len(Hs), 3),
                "converged": np.ones(len(Hs), bool)}
    run.ncases = ncases
    run.cache_state = "stub"
    return run


def _cfg(tmp_path=None, **kw):
    base = dict(queue_max=8, batch_cases=2, window_s=0.02,
                batch_deadline_s=5.0, retry_base_s=0.01,
                degrade_after=99)
    if tmp_path is not None:
        base["journal_dir"] = str(tmp_path)
    base.update(kw)
    return ServeConfig(**base)


# ---------------------------------------------------------------------------
# unit: the shared crash-safe JSONL codec
# ---------------------------------------------------------------------------

def test_journalio_flush_per_line_and_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    w = journalio.JsonlWriter(path, header=lambda part: {"type": "begin",
                                                         "part": part})
    w.write({"type": "rec", "n": 1})
    w.write({"type": "rec", "n": 2})
    # flush-per-line: the bytes are on disk NOW, before close
    docs = journalio.read(path)
    assert [d["type"] for d in docs] == ["begin", "rec", "rec"]
    # a torn tail (crash mid-write) is skipped and COUNTED by kind
    w.write({"type": "rec", "n": 3})
    w.tear_tail()
    w.close()
    docs, bad = journalio.read_counted(path, kind="unittest")
    assert [d.get("n") for d in docs] == [None, 1, 2]
    assert bad == 1
    snap = obs.snapshot()
    series = snap["raft_tpu_journal_corrupt_total"]["series"]
    assert any(s["labels"] == {"kind": "unittest"} and s["value"] == 1.0
               for s in series)


def test_journalio_size_rotation_with_part_headers(tmp_path):
    path = str(tmp_path / "r.jsonl")
    w = journalio.JsonlWriter(path, max_bytes=120, keep=2,
                              header=lambda p: {"type": "begin",
                                                "part": p})
    for i in range(12):
        w.write({"type": "rec", "n": i, "pad": "x" * 20})
    w.close()
    assert os.path.exists(path + ".1")
    docs = journalio.read(path)
    assert docs[0]["type"] == "begin" and docs[0]["part"] == w.part
    # the case-journal metric migration: CaseJournal counts under
    # kind="case" through the same shared counter
    from raft_tpu import recovery
    j = recovery.CaseJournal("k", base_dir=str(tmp_path))
    j.store_case(0, {"x": 1})
    with open(j._path(0), "wb") as f:
        f.write(b"torn")
    assert j.load_case(0) is None
    series = obs.snapshot()["raft_tpu_journal_corrupt_total"]["series"]
    assert any(s["labels"] == {"kind": "case"} for s in series)


# ---------------------------------------------------------------------------
# unit: kill/torn fault grammar
# ---------------------------------------------------------------------------

def test_faults_kill_and_torn_grammar():
    specs = faults.parse(
        "kill@serve:req=7,torn@journal:once,"           # supported
        "kill@dynamics,torn@serve,nan@journal,"         # rejected
        "hang@journal,corrupt@journal,kill@journal")    # rejected
    assert [(f["action"], f["site"]) for f in specs] == \
        [("kill", "serve"), ("torn", "journal")]
    assert specs[0]["match"] == {"req": 7}
    assert specs[1]["times"] == 1
    faults.install("kill@serve:req=2,torn@journal:record=admit")
    try:
        assert faults.fire("serve", req=1) is None
        assert faults.fire("serve", req=2) == "kill"
        assert faults.fire("journal", record="complete") is None
        assert faults.fire("journal", record="admit") == "torn"
    finally:
        faults.clear()


def test_torn_journal_fault_tears_the_wal(tmp_path):
    faults.install("torn@journal:record=complete:once")
    try:
        j = wal.RequestJournal(str(tmp_path), run_id="t")
        j.record_admit(0, "req0", "sha256:r0", 1.0, 8.0, 0.0, 60.0,
                       "default")
        j.record_complete(0, "sha256:r0", "sha256:d0", "full", 0,
                          [1.0] * 6, 3, True)
        j.close()
    finally:
        faults.clear()
    state = wal.replay(str(tmp_path))
    # the complete record was torn mid-write: skipped, counted, and the
    # request correctly classifies as still pending
    assert state["corrupt"] == 1
    assert [r["seq"] for r in state["pending"]] == [0]
    assert state["completed"] == {}


# ---------------------------------------------------------------------------
# unit: WAL record schema + replay classification
# ---------------------------------------------------------------------------

def test_request_journal_records_and_replay(tmp_path):
    j = wal.RequestJournal(str(tmp_path), run_id="r1")
    rd = [wal.request_digest(1.0 + i, 8.0, 0.0) for i in range(4)]
    for i in range(4):
        j.record_admit(i, f"req{i}", rd[i], 1.0 + i, 8.0, 0.0, 60.0,
                       "default")
    j.record_batch(0, [0, 1], "full", "default")
    j.record_complete(0, rd[0], "sha256:d0", "full", 0, [1.0] * 6, 3,
                      True)
    j.record_fail(1, rd[1], {"error": "NonFiniteResult"}, False)
    j.record_tenant("evict", "default", "full")
    j.record_handoff([2, 3], {"default/full": "k"}, 4, "succ")
    j.close()
    state = wal.replay(str(tmp_path))
    assert set(state["admitted"]) == {0, 1, 2, 3}
    assert list(state["completed"]) == [0]
    assert list(state["failed"]) == [1]
    assert [r["seq"] for r in state["pending"]] == [2, 3]
    assert state["max_seq"] == 3 and state["corrupt"] == 0
    assert state["handoff"]["pending"] == [2, 3]
    assert state["by_rdigest"][rd[0]]["digest"] == "sha256:d0"


def test_replay_strict_raises_typed_journal_corrupt(tmp_path):
    j = wal.RequestJournal(str(tmp_path), run_id="r2")
    j.record_admit(0, "req0", "sha256:x", 1.0, 8.0, 0.0, 60.0,
                   "default")
    j.close()
    with open(wal.journal_path(str(tmp_path)), "ab") as f:
        f.write(b'{"type":"admit","seq":1')          # torn tail
    assert wal.replay(str(tmp_path))["corrupt"] == 1
    with pytest.raises(errors.JournalCorrupt) as exc:
        wal.replay(str(tmp_path), strict=True)
    assert isinstance(exc.value, errors.CacheCorruption)
    assert exc.value.ctx["corrupt"] == 1


def test_rotation_checkpoints_open_admits(tmp_path, monkeypatch):
    """Size rotation must never age out an open request's admit
    record: each fresh part re-appends a checkpoint of still-open
    admissions, so replay finds them however much traffic rotated the
    older parts away."""
    monkeypatch.setenv("RAFT_TPU_SERVE_JOURNAL_MAX_BYTES", "500")
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    t = svc.submit(2.0, 9.0, 0.0)     # stays open: service not started
    j = svc._journal
    part0 = j._writer.part
    for _ in range(40):               # enough traffic to rotate twice+
        j.record_tenant("evict", "default", "full")
    assert j._writer.part > part0 + 1
    # the live part no longer holds the ORIGINAL admit line, yet replay
    # still classifies the request as pending via the checkpoint copy
    state = wal.replay(str(tmp_path))
    assert [r["seq"] for r in state["pending"]] == [t.seq]
    assert state["admitted"][t.seq]["checkpoint"] is True
    assert state["admitted"][t.seq]["rdigest"] == \
        wal.request_digest(2.0, 9.0, 0.0)
    svc.start()
    assert t.result(10.0).ok
    svc.stop()
    # terminal: the complete record lands in the live part, and the
    # request no longer rides rotation checkpoints
    assert svc._journal_snapshot() == []


# ---------------------------------------------------------------------------
# unit: WAL-before-ack + recovery semantics
# ---------------------------------------------------------------------------

def test_wal_written_before_ticket_ack(tmp_path):
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    # NOT started: the admit record must hit the WAL at submit time,
    # before the ticket is returned, not when the batch runs
    t = svc.submit(2.5, 9.0, 0.0)
    docs = journalio.read(wal.journal_path(str(tmp_path)))
    admits = [d for d in docs if d["type"] == "admit"]
    assert len(admits) == 1 and admits[0]["seq"] == t.seq
    assert admits[0]["rdigest"] == wal.request_digest(2.5, 9.0, 0.0)
    svc.start()
    res = t.result(10.0)
    svc.stop()
    docs = journalio.read(wal.journal_path(str(tmp_path)))
    comp = [d for d in docs if d["type"] == "complete"]
    batch = [d for d in docs if d["type"] == "batch"]
    assert len(comp) == 1 and comp[0]["digest"] == res.digest
    assert comp[0]["std"] == res.std
    assert batch and batch[0]["seqs"] == [t.seq]


def test_replay_idempotency_matrix(tmp_path):
    """ISSUE satellite: a journal containing a completed digest, a
    duplicate submission, an accepted-unfinished request, and a torn
    tail line — ``recover()`` re-solves exactly the unfinished one,
    dedupes the duplicate, skips the torn line, and the resulting
    digests match a continuous run bit-for-bit."""
    solves = {"batches": 0, "seqs": []}

    def counting_factory(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            solves["batches"] += 1
            solves["seqs"].append(list(np.asarray(Hs)))
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    # the continuous reference: one service solves all three distinct
    # requests in one life
    ref = SweepService(runner_factory=stub_factory,
                       config=_cfg(batch_cases=1))
    ref.start()
    ref_digests = {}
    for seq, hs in enumerate([1.0, 1.0, 5.0]):   # seq1 duplicates seq0
        ref_digests[seq] = ref.submit(hs, 8.0, 0.0).result(10.0).digest
    ref.stop()

    # the crashed life's journal: seq0 completed, seq1 duplicate of it
    # (admitted, unfinished), seq2 unfinished, then a torn tail
    d0 = ref_digests[0]
    rd0 = wal.request_digest(1.0, 8.0, 0.0)
    j = wal.RequestJournal(str(tmp_path), run_id="dead")
    j.record_admit(0, "req0", rd0, 1.0, 8.0, 0.0, 60.0, "default")
    j.record_complete(0, rd0, d0, "full", 0, [1.0] * 6, 3, True)
    j.record_admit(1, "req1", rd0, 1.0, 8.0, 0.0, 60.0, "default")
    j.record_admit(2, "req2", wal.request_digest(5.0, 8.0, 0.0),
                   5.0, 8.0, 0.0, 60.0, "default")
    j.close()
    with open(wal.journal_path(str(tmp_path)), "ab") as f:
        f.write(b'{"type":"admit","seq":3,"Hs":9.9')   # torn tail

    svc = SweepService(runner_factory=counting_factory,
                       config=_cfg(tmp_path, batch_cases=1))
    info = svc.recover()
    assert info["recovered"] == 1 and info["replayed"] == 1
    assert info["deduped"] == 1 and info["corrupt"] == 1
    # the completed digest is fetchable WITHOUT re-solving
    assert svc.fetch(d0).seq == 0
    assert svc.fetch(d0).source == "recovered"
    # the duplicate resolved instantly from the journal
    dup = info["tickets"][1].result(1.0)
    assert dup.ok and dup.digest == d0 and dup.source == "deduped"
    svc.start()
    r2 = info["tickets"][2].result(10.0)
    svc.stop()
    # exactly ONE solve ran: the accepted-unfinished request
    assert solves["batches"] == 1 and solves["seqs"] == [[5.0]]
    assert r2.source == "replayed"
    # digest parity with the continuous run, bit for bit
    assert {0: svc.fetch(d0).digest, 1: dup.digest, 2: r2.digest} == \
        ref_digests
    # idempotent twice over: a second replay of the journal now sees
    # every seq terminal (the dedupe was journaled as complete)
    state = wal.replay(str(tmp_path))
    assert state["pending"] == [] and set(state["completed"]) == {0, 1, 2}


def test_recover_preserves_seqs_and_continues_seq_space(tmp_path):
    j = wal.RequestJournal(str(tmp_path), run_id="dead")
    j.record_admit(5, "req5-orig", wal.request_digest(2.0, 8.0, 0.0),
                   2.0, 8.0, 0.0, 60.0, "default")
    j.close()
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    info = svc.recover()
    svc.start()
    # the replayed request keeps its original admission seq (the
    # deterministic retry/backoff key) AND its original request id
    r5 = info["tickets"][5].result(10.0)
    assert r5.seq == 5 and r5.request_id == "req5-orig"
    # new admissions continue the crashed process's seq space
    t = svc.submit(3.0, 8.0, 0.0)
    assert t.seq == 6
    assert t.result(10.0).ok
    summary = svc.stop()
    assert summary["replayed"] == 1
    assert summary["replayed_lost_count"] == 0
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_journal_replayed_total"]["series"]
    assert any(s["labels"] == {"outcome": "replayed"} for s in series)


def test_recover_unknown_tenant_fails_typed_never_drops(tmp_path):
    j = wal.RequestJournal(str(tmp_path), run_id="dead")
    j.record_admit(0, "req0", "sha256:x", 1.0, 8.0, 0.0, 60.0,
                   "retired-model")
    j.close()
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    info = svc.recover()
    r = info["tickets"][0].result(1.0)
    assert not r.ok and r.error["error"] == "ModelConfigError"
    assert svc.stop()["replayed_lost_count"] == 0


# ---------------------------------------------------------------------------
# unit: graceful drain / handoff
# ---------------------------------------------------------------------------

def test_drain_flushes_work_and_writes_handoff_manifest(tmp_path):
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    svc.start()
    tickets = [svc.submit(1.0 + i, 8.0, 0.0) for i in range(3)]
    doc = svc.drain(successor="http://replacement:8765")
    # in-flight work completed (nothing pending), manifest written
    assert all(t.result(0.1).ok for t in tickets)
    assert doc["pending"] == [] and doc["next_seq"] == 3
    assert doc["successor"] == "http://replacement:8765"
    hpath = wal.handoff_path(str(tmp_path))
    assert os.path.isfile(hpath)
    assert json.load(open(hpath))["schema"] == "raft_tpu.serve.handoff/v1"
    # post-drain admission: 429-style typed reject pointing at the
    # successor
    with pytest.raises(errors.AdmissionRejected) as exc:
        svc.submit(9.0, 8.0, 0.0)
    assert exc.value.ctx["reason"] == "stopped"
    assert exc.value.ctx["successor"] == "http://replacement:8765"


def test_drain_journals_unflushable_work_as_pending(tmp_path):
    def slow_factory(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            time.sleep(1.0)
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    svc = SweepService(runner_factory=slow_factory,
                       config=_cfg(tmp_path, batch_cases=1,
                                   queue_max=8))
    svc.start()
    tickets = [svc.submit(1.0 + i, 8.0, 0.0) for i in range(3)]
    doc = svc.drain(timeout=0.2)          # cannot flush 3s of work
    assert doc["pending"], "slow work should have been handed off"
    # the local tickets resolve typed (handoff), never hang silently
    done = [t.result(0.1) for t in tickets if t.done()]
    assert all(r.ok or r.error["error"] == "DeadlineExceeded"
               for r in done)
    # ... and the WAL never drops anything: every admitted seq is
    # either terminal (the in-flight batch may legitimately finish —
    # and journal — during teardown) or still pending for the
    # successor; the handoff snapshot is conservative (a superset of
    # what remains pending after teardown)
    state = wal.replay(str(tmp_path))
    wal_pending = {r["seq"] for r in state["pending"]}
    assert wal_pending | set(state["completed"]) == {0, 1, 2}
    assert wal_pending <= set(doc["pending"])
    assert wal_pending, "the queued requests never ran: must stay pending"
    assert state["handoff"]["pending"] == doc["pending"]


# ---------------------------------------------------------------------------
# unit: multi-tenant warm runners
# ---------------------------------------------------------------------------

class _Marker:
    def __init__(self, marker):
        self.marker = marker
        self.w = np.arange(3)


def test_tenant_registry_typed_misconfig():
    with pytest.raises(errors.ModelConfigError):
        TenantRegistry(max_live_programs=0)
    reg = TenantRegistry(max_live_programs=1)
    reg.add("a", {"full": object()})
    with pytest.raises(errors.ModelConfigError):
        reg.add("a", {"full": object()})              # duplicate
    with pytest.raises(errors.ModelConfigError) as exc:
        reg.require("nope")
    assert exc.value.ctx["tenant"] == "nope"
    with pytest.raises(errors.ModelConfigError):
        SweepService(runner_factory=stub_factory, config=_cfg(),
                     tenants=[Tenant("default")])     # reserved name


def test_multi_tenant_requests_solve_on_their_own_models():
    svc = SweepService(_Marker(0.0), config=_cfg(),
                       runner_factory=stub_factory,
                       tenants=[Tenant("modelB", _Marker(100.0))])
    svc.start()
    ta = svc.submit(1.0, 8.0, 0.0)
    tb = svc.submit(1.0, 8.0, 0.0, tenant="modelB")
    with pytest.raises(errors.ModelConfigError):
        svc.submit(1.0, 8.0, 0.0, tenant="modelC")
    ra, rb = ta.result(10.0), tb.result(10.0)
    summary = svc.stop()
    # same physics request, different tenant model — and the batches
    # never mixed (the marker offset proves which program served it)
    assert np.allclose(ra.std, 1.0) and ra.tenant == "default"
    assert np.allclose(rb.std, 101.0) and rb.tenant == "modelB"
    assert rb.digest != ra.digest
    tenants = summary["tenancy"]["tenants"]
    assert tenants["default"]["completed"] == 1
    assert tenants["modelB"]["completed"] == 1
    snap = obs.snapshot()
    series = snap["raft_tpu_serve_tenant_requests_total"]["series"]
    assert any(s["labels"] == {"tenant": "modelB", "outcome": "completed"}
               for s in series)


def test_tenant_lru_eviction_and_rewarm_under_budget(tmp_path):
    svc = SweepService(_Marker(0.0),
                       config=_cfg(tmp_path, max_live_programs=1,
                                   batch_cases=1),
                       runner_factory=stub_factory,
                       tenants=[Tenant("modelB", _Marker(100.0))])
    svc.start()
    # A, B (evicts A), A again (evicts B, REWARMS A)
    assert svc.submit(1.0, 8.0, 0.0).result(10.0).ok
    assert svc.submit(1.0, 8.0, 0.0, tenant="modelB").result(10.0).ok
    assert svc.submit(2.0, 8.0, 0.0).result(10.0).ok
    summary = svc.stop()
    fac = summary["tenancy"]
    assert fac["live_programs"] == 1
    assert fac["evictions"] == 2 and fac["rewarms"] == 1
    assert summary["tenant_evictions"] == 2
    snap = obs.snapshot()
    ev = snap["raft_tpu_serve_tenant_evictions_total"]["series"]
    assert any(s["labels"] == {"tenant": "default", "mode": "full"}
               for s in ev)
    # evictions/re-warms are journaled
    docs = journalio.read(wal.journal_path(str(tmp_path)))
    tevents = [(d["event"], d["tenant"]) for d in docs
               if d["type"] == "tenant"]
    assert ("evict", "default") in tevents
    assert ("rewarm", "default") in tevents


# ---------------------------------------------------------------------------
# unit: recovered-service manifest -> trend row -> restart SLO rules
# ---------------------------------------------------------------------------

def test_recovered_serve_manifest_trend_row_and_slo(tmp_path,
                                                    monkeypatch):
    from raft_tpu.obs import trendstore as T

    jdir = tmp_path / "journal"
    j = wal.RequestJournal(str(jdir), run_id="dead")
    j.record_admit(0, "req0", wal.request_digest(2.0, 8.0, 0.0),
                   2.0, 8.0, 0.0, 60.0, "default")
    j.close()
    monkeypatch.setenv("RAFT_TPU_TREND_DB", str(tmp_path / "t.sqlite"))
    obs.configure(str(tmp_path / "obs"))
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(jdir))
    info = svc.recover()
    svc.start()
    run_id = svc._manifest.run_id
    assert info["tickets"][0].result(10.0).ok
    summary = svc.stop()
    assert summary["replayed"] == 1
    assert summary["replayed_lost_count"] == 0
    doc = json.loads((tmp_path / "obs" /
                      f"serve_{run_id}.manifest.json").read_text())
    assert doc["extra"]["serve"]["recovery"]["replayed"] == 1
    store = T.TrendStore(str(tmp_path / "t.sqlite"))
    rows = store.rows(kind="serve")
    facts = rows[0]["facts"]
    assert facts["serve_replayed"] == 1
    assert facts["serve_replayed_lost_count"] == 0
    # stub runners never come from the exec cache -> warm-start fact 0;
    # the rule correctly fires on a recovered service that re-traced
    assert facts["serve_restart_warm_start"] == 0
    report = T.evaluate_slo(rows)
    by_name = {r["name"]: r for r in report["results"]}
    assert not by_name["serve_replayed_lost_count"]["skipped"]
    assert by_name["serve_replayed_lost_count"]["ok"]
    assert not by_name["serve_restart_warm_start"]["skipped"]
    assert not by_name["serve_restart_warm_start"]["ok"]


# ---------------------------------------------------------------------------
# integration: the ISSUE kill-restart acceptance (subprocess, coarse
# cylinder model, exec-cache warm start)
# ---------------------------------------------------------------------------

def test_kill_restart_acceptance(tmp_path, monkeypatch):
    """A journaled child service is hard-killed (``kill@serve`` ->
    ``os._exit(137)``) mid-batch; the successor recovers the same
    journal dir: zero accepted requests lost, every completed request
    digest-identical to an uninterrupted clean run, warm start from
    the executable cache, graceful drain writing the handoff
    manifest."""
    from raft_tpu.parallel import exec_cache
    from raft_tpu.serve import soak

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR",
                       str(tmp_path / "cache"))
    exec_cache.reset_memo()
    jdir = tmp_path / "journal"
    report = soak.run_kill_restart(journal_dir=str(jdir),
                                   n_requests=10, kill_at=6)
    assert report["ok"], {k: report[k] for k in
                          ("killed", "child_rc", "lost",
                           "digest_mismatches", "recover")}
    # the injected kill really fired, mid-batch, with work on the books
    assert report["child_rc"] == 137
    assert 0 < report["pre_kill_completed"] < report["n_requests"]
    # completed-before-kill results were restored WITHOUT re-solving,
    # the unfinished remainder was replayed, nothing was lost
    rec = report["recover"]
    assert rec["recovered"] == report["pre_kill_completed"]
    assert rec["recovered"] + rec["replayed"] == report["n_requests"]
    assert report["lost"] == [] and report["digest_mismatches"] == []
    assert report["replayed_lost_count"] == 0
    # the successor deserialized the SAME warm program (no recompile)
    assert report["restart_warm_start"] == 1
    assert report["summary"]["unhandled"] == 0
    # the drain handed off cleanly: nothing pending, exec-cache keys
    # named for the NEXT successor
    assert report["handoff"]["pending"] == []
    assert report["handoff"]["exec_keys"]
    assert os.path.isfile(wal.handoff_path(str(jdir)))
