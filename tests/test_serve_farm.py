"""Farm tenant mode of the sweep service: POST /farm's service path.

One coarse OC3 spar (4 frequency bins, real rotor so the BEM
power/thrust curve and the aero-damping table engage) driven through
submit_farm: admission -> WAL -> the warm farm runner on the shared
long-request lane -> result digest -> dedupe -> crash recovery.
Mirrors the durability contract of the optimize tenant
(tests/test_serve_durability.py): every acked admission survives a
stop/restart and re-delivers the identical payload.
"""
import numpy as np
import pytest

from raft_tpu.serve import ServeConfig, SweepService
from raft_tpu.serve import journal as wal
from raft_tpu.serve.soak import build_fowt

pytestmark = pytest.mark.filterwarnings("ignore::UserWarning")

SPEC = {"layout": [[0.0, 0.0], [800.0, 0.0]],
        "Hs": [1.0, 2.0], "Tp": [8.0, 10.0], "beta": [0.0, 0.1],
        "U_inf": [10.0, 12.0]}


@pytest.fixture(scope="module")
def spar_fowt():
    return build_fowt("OC3spar.yaml", min_freq=0.1, max_freq=0.5,
                      dfreq=0.1)


def test_farm_tenant_round_trip(spar_fowt, tmp_path):
    cfg = ServeConfig(journal_dir=str(tmp_path / "wal"), nIter=4)
    svc = SweepService(spar_fowt, cfg)
    svc.start()
    try:
        t = svc.submit_farm(SPEC)
        res = t.result(300.0)
        assert res.ok and res.mode == "farm" and res.source == "solved"
        ex = res.extra
        assert ex["n_turbines"] == 2 and ex["ncases"] == 2
        std = np.asarray(ex["std"])
        assert std.shape == (2, 2, 6) and np.all(np.isfinite(std))
        U = np.asarray(ex["U_wake"])
        # wind flows along +x over the [0, 800] m row: the downwind
        # turbine is waked, the upwind one sees the free stream
        assert np.allclose(U[0], SPEC["U_inf"], atol=1e-6)
        assert np.all(U[1] < np.asarray(SPEC["U_inf"]) - 0.1)
        assert ex["layout_digest"] and ex["provenance"]["cache_state"] \
            in ("miss", "hit", "disabled")

        # duplicate admission: served from the digest index, no second
        # solve, identical result digest
        r2 = svc.submit_farm(SPEC).result(30.0)
        assert r2.source == "deduped" and r2.digest == res.digest

        # the admission digest is salted with the layout: moving one
        # turbine is a DIFFERENT request even with identical sea states
        moved = dict(SPEC, layout=[[0.0, 0.0], [900.0, 0.0]])
        assert wal.farm_digest(SPEC, "default") != \
            wal.farm_digest(moved, "default")
    finally:
        svc.stop()

    # crash recovery: a fresh service over the same WAL re-delivers the
    # completed farm result by digest without re-solving
    svc2 = SweepService(spar_fowt, cfg)
    try:
        info = svc2.recover()
        assert info["recovered"] >= 1
        got = svc2.fetch(res.digest)
        assert got is not None
        assert got.extra["std_norm"] == res.extra["std_norm"]
        assert got.extra["wake_iters"] == res.extra["wake_iters"]
    finally:
        svc2.stop()


def test_farm_admission_caps_are_typed(spar_fowt):
    from raft_tpu import errors

    cfg = ServeConfig(farm_turbines_max=2, farm_cases_max=4)
    svc = SweepService(spar_fowt, cfg)
    with pytest.raises(errors.ModelConfigError, match="cap"):
        svc.submit_farm(dict(SPEC, layout=[[0.0, 0.0], [500.0, 0.0],
                                           [1000.0, 0.0]]))
    with pytest.raises(errors.ModelConfigError, match="cap"):
        n = 5
        svc.submit_farm(dict(SPEC, Hs=[1.0] * n, Tp=[8.0] * n,
                             beta=[0.0] * n, U_inf=[10.0] * n))
    assert svc.stop()["completed"] == 0
