"""Replicated serving (raft_tpu/serve replica + router + failover).

Unit tier (stub batch engines, no solves): the journalio replication
hooks, the drop/lag fault grammar, WAL mirroring parity (mirror ==
primary replay, rotation parity, torn mirror tail skip-and-counted),
catch-up resync after a dropped part, the typed ``ReplicaLagExceeded``
degradation signal (and its fold into the service ladder), recovery
from a mirror alone in a fresh directory tree, duplicate delivery
across replicas deduped by request digest, the replica router
(token-bucket quotas, shared-secret auth, tenant-affinity routing,
failover, re-resolution by rdigest), the replication/failover
trend-store facts + SLO rules, and the ``bench.py serve``
sustained-throughput facts.

Integration tier (one coarse Vertical_cylinder model): a meshed
service reproduces the unmeshed digests on virtual devices, and the
ISSUE failover acceptance — child A's mirrored WAL SIGKILLed
mid-batch, successor B recovering from ONLY the mirror in a fresh
tree with zero accepted requests lost and bit-for-bit digest parity.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from raft_tpu import errors, obs
from raft_tpu.obs import journalio
from raft_tpu.serve import ReplicaRouter, ServeConfig, SweepService
from raft_tpu.serve import journal as wal
from raft_tpu.serve.replica import WalMirror
from raft_tpu.serve.router import TokenBucket, make_server, parse_quota
from raft_tpu.testing import faults


def stub_factory(mode, fowt, ncases, **kw):
    """Deterministic instant batch engine (std row = Hs replicated)."""
    def run(Hs, Tp, beta):
        Hs = np.asarray(Hs)
        return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                "iters": np.full(len(Hs), 3),
                "converged": np.ones(len(Hs), bool)}
    run.ncases = ncases
    run.cache_state = "stub"
    return run


def _cfg(journal_dir=None, mirror_dirs=None, **kw):
    base = dict(queue_max=16, batch_cases=2, window_s=0.02,
                batch_deadline_s=5.0, retry_base_s=0.01,
                degrade_after=99)
    if journal_dir is not None:
        base["journal_dir"] = str(journal_dir)
    if mirror_dirs is not None:
        base["mirror_dirs"] = tuple(str(d) for d in mirror_dirs)
    base.update(kw)
    return ServeConfig(**base)


# ---------------------------------------------------------------------------
# unit: journalio replication hooks
# ---------------------------------------------------------------------------

def test_journalio_post_flush_and_post_rotate_hooks(tmp_path):
    calls = {"flush": 0, "rotate": []}
    w = journalio.JsonlWriter(
        str(tmp_path / "j.jsonl"), max_bytes=80, keep=2,
        post_flush=lambda writer: calls.__setitem__(
            "flush", calls["flush"] + 1),
        post_rotate=lambda writer, sealed: calls["rotate"].append(sealed))
    for i in range(6):
        w.write({"type": "rec", "n": i, "pad": "x" * 30})
    w.close()
    # every write+flush notified; each sealed generation notified with
    # its part index, in order
    assert calls["flush"] >= 6
    assert calls["rotate"] == list(range(len(calls["rotate"])))
    assert len(calls["rotate"]) >= 2

    # a broken hook must never break the write itself
    w2 = journalio.JsonlWriter(
        str(tmp_path / "k.jsonl"),
        post_flush=lambda writer: (_ for _ in ()).throw(OSError("peer")))
    w2.write({"type": "rec"})
    w2.close()
    assert [d["type"] for d in journalio.read(str(tmp_path / "k.jsonl"))] \
        == ["rec"]


# ---------------------------------------------------------------------------
# unit: drop/lag fault grammar
# ---------------------------------------------------------------------------

def test_faults_drop_lag_grammar():
    specs = faults.parse(
        "drop@replica:part=2,lag@replica:s=1.5,lag@replica:ms=250,"  # ok
        "drop@serve,lag@journal,nan@replica,raise@replica,"          # no
        "hang@replica,kill@replica,torn@replica,corrupt@replica")    # no
    assert [(f["action"], f["site"]) for f in specs] == \
        [("drop", "replica"), ("lag", "replica"), ("lag", "replica")]
    assert specs[0]["match"] == {"part": 2}
    assert specs[1]["lag_s"] == 1.5
    assert specs[2]["lag_s"] == 0.25
    # a bare lag spec carries the default deferral
    assert faults.parse("lag@replica")[0]["lag_s"] == 2.0
    faults.install("drop@replica:part=1:once")
    try:
        assert faults.fire("replica", part=0) is None
        assert faults.fire("replica", part=1) == "drop"
        assert faults.fire("replica", part=1) is None     # once
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# unit: WAL mirroring parity
# ---------------------------------------------------------------------------

def test_mirror_matches_primary_replay(tmp_path):
    primary, mirror = str(tmp_path / "p"), str(tmp_path / "m")
    j = wal.RequestJournal(primary, run_id="r", mirror_dirs=[mirror])
    rd = [wal.request_digest(1.0 + i, 8.0, 0.0) for i in range(4)]
    for i in range(4):
        j.record_admit(i, f"req{i}", rd[i], 1.0 + i, 8.0, 0.0, 60.0,
                       "default")
    j.record_batch(0, [0, 1], "full", "default")
    j.record_complete(0, rd[0], "sha256:d0", "full", 0, [1.0] * 6, 3,
                      True)
    j.record_fail(1, rd[1], {"error": "NonFiniteResult"}, False)
    # synchronous mirroring: the peer is current BEFORE close
    assert j.mirror.status()["lag_records"] == 0
    j.close()
    sp, sm = wal.replay(primary), wal.replay(mirror)
    # the mirror replays EXACTLY like the primary
    assert sp["admitted"].keys() == sm["admitted"].keys()
    assert sp["completed"].keys() == sm["completed"].keys()
    assert sp["failed"].keys() == sm["failed"].keys()
    assert [r["seq"] for r in sp["pending"]] == \
        [r["seq"] for r in sm["pending"]] == [2, 3]
    assert sp["records"] == sm["records"]
    assert sm["by_rdigest"][rd[0]]["digest"] == "sha256:d0"


def test_mirror_rotation_parity_and_two_peers(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_SERVE_JOURNAL_MAX_BYTES", "600")
    primary = str(tmp_path / "p")
    peers = [str(tmp_path / "m1"), str(tmp_path / "m2")]
    j = wal.RequestJournal(primary, run_id="r", mirror_dirs=peers)
    for i in range(40):
        j.record_admit(i, f"r{i}", f"s{i}", 1.0, 8.0, 0.0, 60.0,
                       "default")
    assert j._writer.part >= 2          # really rotated
    j.close()
    sp = wal.replay(primary)
    for peer in peers:
        sm = wal.replay(peer)
        assert sm["admitted"].keys() == sp["admitted"].keys()
        assert sm["records"] == sp["records"]
    st = j.mirror.status()
    assert st["lag_records"] == 0 and st["errors"] == 0
    assert set(st["peers"]) == set(peers)


def test_drop_fault_catchup_resync(tmp_path, monkeypatch):
    """ISSUE satellite: ``drop@replica:part=N`` swallows one sealed
    part's ship; the peer visibly lags (metric + lag accounting) until
    a reconciliation pass re-ships it by size comparison."""
    monkeypatch.setenv("RAFT_TPU_SERVE_JOURNAL_MAX_BYTES", "600")
    primary, mirror = str(tmp_path / "p"), str(tmp_path / "m")
    faults.install("drop@replica:part=0")
    try:
        j = wal.RequestJournal(primary, run_id="r",
                               mirror_dirs=[mirror])
        while j._writer.part == 0:      # exactly one rotation
            j.record_tenant("evict", "default", "full")
        # one post-rotation write so the lag gauge refolds with the
        # swallowed sealed part on the books
        j.record_tenant("evict", "default", "full")
        lags = j.mirror.lag_records()
        assert max(lags.values()) > 0
        assert not os.path.exists(
            os.path.join(mirror, wal.FILENAME + ".1"))
        snap = obs.snapshot()
        g = snap["raft_tpu_serve_wal_replication_lag_records"]["series"]
        assert any(s["labels"] == {"peer": mirror} and s["value"] > 0
                   for s in g)
        # catch-up resync converges by size reconciliation
        j.mirror.sync_now()
        assert max(j.mirror.lag_records().values()) == 0
        assert os.path.exists(
            os.path.join(mirror, wal.FILENAME + ".1"))
        j.close()
    finally:
        faults.clear()
    assert wal.replay(mirror)["records"] == wal.replay(primary)["records"]


def test_lag_fault_trips_typed_replica_lag_exceeded(tmp_path):
    """ISSUE satellite: ``lag@replica:s=S`` defers mirroring; lag past
    the budget raises the typed degradation signal from ``check()``,
    and a graceful close catches the peer up and clears it."""
    primary, mirror = str(tmp_path / "p"), str(tmp_path / "m")
    faults.install("lag@replica:s=30")
    try:
        j = wal.RequestJournal(primary, run_id="r",
                               mirror_dirs=[mirror], mirror_max_lag=3)
        for i in range(6):
            j.record_admit(i, f"r{i}", f"s{i}", 1.0, 8.0, 0.0, 60.0,
                           "default")
        assert j.mirror.lag_exceeded
        with pytest.raises(errors.ReplicaLagExceeded) as exc:
            j.mirror.check()
        assert exc.value.ctx["max_lag_records"] == 3
        assert exc.value.ctx["lag"] >= 4
    finally:
        faults.clear()
    j.close()                            # final sync, fault cleared
    assert not j.mirror.lag_exceeded
    assert j.mirror.status()["lag_records"] == 0
    assert wal.replay(mirror)["records"] == wal.replay(primary)["records"]


def test_mirror_config_validation():
    with pytest.raises(errors.ModelConfigError):
        ServeConfig(mirror_dirs=("peer",))          # mirrors need a WAL
    with pytest.raises(errors.ModelConfigError):
        ServeConfig(journal_dir="j", mirror_dirs=("j",))  # self-mirror
    with pytest.raises(errors.ModelConfigError):
        ServeConfig(journal_dir="j", mirror_dirs=("m",),
                    replica_max_lag_records=0)


# ---------------------------------------------------------------------------
# unit: service-level replication + failover semantics
# ---------------------------------------------------------------------------

def test_service_mirrors_wal_and_reports_replication_facts(tmp_path):
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path / "primary",
                                   [tmp_path / "mirror"]))
    svc.start()
    t = svc.submit(2.0, 9.0, 0.0)
    assert t.result(10.0).ok
    # fetch by REQUEST digest (the router's re-resolution path)
    res = svc.fetch_rdigest(wal.request_digest(2.0, 9.0, 0.0))
    assert res is not None and res.seq == t.seq
    assert svc.fetch_rdigest("sha256:nope") is None
    summary = svc.stop()
    assert summary["replication_lag_records"] == 0
    assert summary["replication_errors"] == 0
    assert summary["replication"]["peers"]
    # mirrored-but-never-recovered lives carry NO failover facts: the
    # cross-host SLO rules must skip ordinary rows
    assert "failover" not in summary and "failover_lost_count" not in summary


def test_recover_from_mirror_only_in_fresh_tree(tmp_path):
    """The tentpole recover semantics: host A's mirrored WAL replays on
    host B from ONLY the mirror — fresh journal tree, the primary never
    read, a torn mirror live-part tail skip-and-counted — with failover
    facts on the successor's summary."""
    # host A: a live mirrored service completes seq0; seq1's batch
    # wedges mid-solve (the gate) and A's WAL writer is torn away —
    # the admit reached primary AND mirror before the ticket returned
    # (WAL-before-ack), the complete never will: exactly the
    # killed-mid-batch window
    gate = threading.Event()

    def gated_factory(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            if float(np.asarray(Hs)[0]) == 5.0:
                gate.wait(20.0)          # the doomed batch hangs here
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    a = SweepService(runner_factory=gated_factory,
                     config=_cfg(tmp_path / "A" / "journal",
                                 [tmp_path / "shared-mirror"],
                                 batch_cases=1,
                                 batch_deadline_s=60.0))
    done = a.submit(2.0, 9.0, 0.0)
    pend = a.submit(5.0, 9.0, 0.0)
    a.start()
    d0 = done.result(10.0).digest
    time.sleep(0.2)                      # the doomed batch reaches the
    a._journal._writer.close()           # gate; then "host A dies"
    # the mirror additionally carries a torn live-part tail (the dying
    # write a crash can leave) that the PRIMARY never got
    mirror_live = os.path.join(str(tmp_path / "shared-mirror"),
                               wal.FILENAME)
    with open(mirror_live, "ab") as f:
        f.write(b'{"type":"admit","seq":9')       # torn mirror tail
    # host B: FRESH tree, recovers from the mirror alone
    b = SweepService(runner_factory=stub_factory,
                     config=_cfg(tmp_path / "B" / "journal",
                                 [tmp_path / "B" / "mirror"]))
    info = b.recover(str(tmp_path / "shared-mirror"))
    assert info["mirror"] is True
    assert info["recovered"] == 1 and info["replayed"] == 1
    assert info["corrupt"] == 1          # the torn mirror tail, counted
    assert b.fetch(d0).source == "recovered"
    b.start()
    r = info["tickets"][pend.seq].result(10.0)
    assert r.ok and r.source == "replayed" and r.seq == pend.seq
    summary = b.stop()
    assert summary["failover"] == 1
    assert summary["failover_lost_count"] == 0
    assert summary["replayed_lost_count"] == 0
    # B's own journal now carries the replayed complete — the NEXT
    # failover (from B's mirror) would re-deliver without re-solving
    sb = wal.replay(str(tmp_path / "B" / "journal"))
    assert pend.seq in sb["completed"]
    # and B's own mirror is current (a failed-over service is itself
    # failover-ready)
    sbm = wal.replay(str(tmp_path / "B" / "mirror"))
    assert pend.seq in sbm["completed"]
    gate.set()                           # release A's wedged worker
    a.stop(timeout=5.0)


def test_duplicate_delivery_across_replicas_dedupes_by_rdigest(tmp_path):
    """ISSUE satellite: the same physics admitted on TWO replicas (a
    router retry straddling a failover) resolves once — the second
    replay recognizes the request digest and re-delivers the payload
    instead of re-solving."""
    solves = {"n": 0}

    def counting_factory(mode, fowt, ncases, **kw):
        inner = stub_factory(mode, fowt, ncases, **kw)

        def run(Hs, Tp, beta):
            solves["n"] += 1
            return inner(Hs, Tp, beta)
        run.ncases = ncases
        return run

    rd = wal.request_digest(2.0, 9.0, 0.0)
    # replica A completed the request (its WAL says so)
    ja = wal.RequestJournal(str(tmp_path / "walA"), run_id="A")
    ja.record_admit(0, "reqA", rd, 2.0, 9.0, 0.0, 60.0, "default")
    ja.record_complete(0, rd, "sha256:dA", "full", 0, [2.0] * 6, 3,
                       True)
    ja.close()
    # replica B admitted the SAME physics but died before solving
    jb = wal.RequestJournal(str(tmp_path / "walB"), run_id="B")
    jb.record_admit(3, "reqB", rd, 2.0, 9.0, 0.0, 60.0, "default")
    jb.close()
    svc = SweepService(runner_factory=counting_factory,
                       config=_cfg(tmp_path / "journal"))
    svc.recover(str(tmp_path / "walA"))
    info = svc.recover(str(tmp_path / "walB"))
    res = info["tickets"][3].result(1.0)
    assert res.ok and res.source == "deduped"
    assert res.digest == "sha256:dA" and res.request_id == "reqB"
    assert info["deduped"] == 1 and solves["n"] == 0
    summary = svc.stop()
    assert summary["recovery"]["recovered"] == 1
    assert summary["recovery"]["deduped"] == 1
    # the dedupe was journaled terminal: B's seq replays complete here
    assert 3 in wal.replay(str(tmp_path / "journal"))["completed"]


def test_second_fold_remaps_colliding_seqs_never_aliases(tmp_path):
    """Two dead replicas' journals both carry a pending seq 3 with
    DIFFERENT physics: folding both must remap the second onto fresh
    seq space (no _open/_replayed_pending aliasing), re-journal the
    inherited admits into OUR WAL, and solve BOTH requests — the
    zero-loss guarantee across overlapping seq spaces."""
    ja = wal.RequestJournal(str(tmp_path / "walA"), run_id="A")
    ja.record_admit(3, "reqA3", wal.request_digest(2.0, 9.0, 0.0),
                    2.0, 9.0, 0.0, 60.0, "default")
    ja.close()
    # journal B overlaps A's seq space (pending 3) AND carries a
    # pending seq (10) ABOVE this life's post-fold-A counter — a remap
    # of B's seq 3 must not land on B's own still-unprocessed seq 10
    jb = wal.RequestJournal(str(tmp_path / "walB"), run_id="B")
    jb.record_admit(3, "reqB3", wal.request_digest(7.0, 9.0, 0.0),
                    7.0, 9.0, 0.0, 60.0, "default")
    jb.record_admit(10, "reqB10", wal.request_digest(8.0, 9.0, 0.0),
                    8.0, 9.0, 0.0, 60.0, "default")
    jb.close()
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path / "journal", batch_cases=1))
    infoA = svc.recover(str(tmp_path / "walA"))
    infoB = svc.recover(str(tmp_path / "walB"))
    # both callers address their ticket by THEIR journal's seq
    ta, tb = infoA["tickets"][3], infoB["tickets"][3]
    tb10 = infoB["tickets"][10]
    assert ta is not tb
    # ... and the service tracks three distinct open requests
    assert len(svc._journal_snapshot()) == 3
    svc.start()
    ra, rb, rb10 = ta.result(10.0), tb.result(10.0), tb10.result(10.0)
    summary = svc.stop()
    assert ra.ok and rb.ok and rb10.ok
    assert len({ra.digest, rb.digest, rb10.digest}) == 3
    assert ra.seq == 3                        # first fold keeps seqs
    assert rb10.seq == 10                     # non-colliding seq kept
    assert rb.seq > 10                        # remapped PAST the
    assert ra.request_id == "reqA3"           # fold's own max_seq
    assert rb.request_id == "reqB3" and rb10.request_id == "reqB10"
    assert summary["replayed"] == 3
    assert summary["replayed_lost_count"] == 0
    # the inherited admits were re-journaled: OUR journal replays all
    # three terminal on its own
    state = wal.replay(str(tmp_path / "journal"))
    assert state["pending"] == []
    assert {ra.seq, rb.seq, rb10.seq} <= set(state["completed"])


def test_replica_lag_folds_into_service_degradation_ladder(tmp_path):
    """A mirror behind budget is an SLO violation the ladder acts on:
    consecutive lagging batches step the service into ``reject`` and
    admission sheds with the typed degraded reason."""
    faults.install("lag@replica:s=30")
    try:
        svc = SweepService(
            runner_factory=stub_factory,
            config=_cfg(tmp_path / "p", [tmp_path / "m"],
                        batch_cases=1, degrade_after=2,
                        replica_max_lag_records=1, reject_hold_s=60.0))
        svc.start()
        deadline = time.monotonic() + 10.0
        seq = 0
        while svc.mode != "reject" and time.monotonic() < deadline:
            try:
                svc.submit(1.0 + seq, 8.0, 0.0).result(5.0)
            except errors.AdmissionRejected:
                break
            seq += 1
        assert svc.stats()["replica_lag_exceeded"] is True
        assert svc.mode == "reject"
        with pytest.raises(errors.AdmissionRejected) as exc:
            svc.submit(9.0, 8.0, 0.0)
        assert exc.value.ctx["reason"] == "degraded"
    finally:
        faults.clear()
        svc.stop()


# ---------------------------------------------------------------------------
# unit: the replica router
# ---------------------------------------------------------------------------

def test_token_bucket_and_quota_parsing():
    assert parse_quota("2.5") == (2.5, 2.5)
    assert parse_quota("10:40") == (10.0, 40.0)
    b = TokenBucket(1.0, 2.0)
    now = time.monotonic() + 100.0
    ok1, _ = b.take(now)
    ok2, _ = b.take(now)
    ok3, after = b.take(now)
    assert (ok1, ok2, ok3) == (True, True, False)
    assert after == pytest.approx(1.0)   # exactly one refill away
    ok4, _ = b.take(now + 1.0)
    assert ok4
    # zero-rate tenant: hard shed with a bounded hint
    blocked = TokenBucket(0.0, 1.0)
    assert blocked.take(now)[0] is True
    ok, after = blocked.take(now)
    assert not ok and after == 3600.0


def test_router_typed_admission_reasons():
    router = ReplicaRouter(["http://127.0.0.1:9"], secret="s",
                           quotas={"t": (0.0, 1.0)})
    # unauthorized beats everything
    with pytest.raises(errors.AdmissionRejected) as exc:
        router.admit("t", token="wrong")
    assert exc.value.ctx["reason"] == "unauthorized"
    router.backends[0].healthy = True
    # burst of 1 admits once, then quota_exceeded with a retry hint
    router.admit("t", token="s")
    with pytest.raises(errors.AdmissionRejected) as exc:
        router.admit("t", token="s")
    assert exc.value.ctx["reason"] == "quota_exceeded"
    assert exc.value.retry_after_s == 3600.0
    # no backend healthy (quota passes first — reasons are ordered
    # auth -> quota -> reachability)
    router.backends[0].healthy = False
    with pytest.raises(errors.AdmissionRejected) as exc:
        router.admit("other", token="s")
    assert exc.value.ctx["reason"] == "no_healthy_replica"
    with pytest.raises(errors.ModelConfigError):
        ReplicaRouter([])
    with pytest.raises(errors.ModelConfigError):
        ReplicaRouter(["http://a", "http://a"])


class _StubReplica:
    """Minimal raftserve-shaped backend for router tests."""

    def __init__(self, name):
        self.name = name
        self.results = {}
        self.by_rdigest = {}
        self.nsub = 0
        self.last_trace = None
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, doc):
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse
                u = urlparse(self.path)
                q = parse_qs(u.query)
                if u.path == "/healthz":
                    self._send(200, {"ok": True, "queue_depth": 0})
                elif u.path == "/result":
                    rid = q.get("id", [None])[0]
                    rd = q.get("rdigest", [None])[0]
                    if rid and rid in outer.results:
                        self._send(200, outer.results[rid])
                    elif rd and rd in outer.by_rdigest:
                        self._send(200, outer.by_rdigest[rd])
                    else:
                        self._send(404, {"error": "unknown"})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                import math
                n = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(n) or b"{}")
                outer.last_trace = self.headers.get("X-Raft-Trace")
                outer.nsub += 1
                rid = f"{outer.name}-req{outer.nsub}"
                beta = math.radians(float(doc.get("heading_deg", 0.0)))
                rd = wal.request_digest(
                    float(doc["hs"]), float(doc["tp"]), beta,
                    doc.get("tenant", "default"))
                res = {"ok": True, "request_id": rid,
                       "served_by": outer.name}
                outer.results[rid] = res
                outer.by_rdigest[rd] = res
                self._send(202, {"request_id": rid, "seq": outer.nsub})

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"

    def shutdown(self):
        self.srv.shutdown()
        self.srv.server_close()


def _post(url, doc, token=None):
    headers = {"X-Raft-Auth": token} if token else {}
    req = urllib.request.Request(url + "/submit",
                                 data=json.dumps(doc).encode(),
                                 method="POST", headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_router_quota_auth_affinity_failover_http():
    """The ISSUE router acceptance: 401 on bad auth, 429 +
    Retry-After for the over-quota tenant while the healthy tenant's
    traffic is unaffected, tenant-affinity routing, failover to the
    survivor when a replica dies, re-resolution by rdigest, and 503
    when nothing is healthy."""
    a, b = _StubReplica("A"), _StubReplica("B")
    router = ReplicaRouter([a.url, b.url], secret="s3",
                           quotas={"small": (0.0, 1.0)},
                           default_quota=(100.0, 100.0),
                           health_interval_s=30.0).start()
    srv = make_server(router, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        code, body, _ = _post(url, {"hs": 2, "tp": 9}, token="bad")
        assert code == 401 and body["reason"] == "unauthorized"
        # tenant "small": burst 1 -> first in, second 429 + Retry-After
        c1, _, _ = _post(url, {"hs": 2, "tp": 9, "tenant": "small"},
                         token="s3")
        c2, b2, h2 = _post(url, {"hs": 2, "tp": 9, "tenant": "small"},
                           token="s3")
        assert c1 == 202 and c2 == 429
        assert b2["reason"] == "quota_exceeded"
        assert int(h2["Retry-After"]) >= 1
        # ... while the default tenant sails through (isolation)
        c3, b3, _ = _post(url, {"hs": 2.5, "tp": 9}, token="s3")
        assert c3 == 202
        pinned = b3["replica"]
        # affinity: the tenant sticks to its warm replica
        c4, b4, _ = _post(url, {"hs": 3.0, "tp": 9}, token="s3")
        assert c4 == 202 and b4["replica"] == pinned
        # fetch by id routes to the owner
        with urllib.request.urlopen(
                url + "/result?id=" + b3["request_id"], timeout=5) as r:
            got = json.loads(r.read())
        assert got["ok"] and got["replica"] == pinned
        # the owning replica dies; the survivor (which replayed the
        # mirror) knows the physics by rdigest
        dead = a if pinned == a.url else b
        surv = b if dead is a else a
        surv.by_rdigest.update(dead.by_rdigest)
        dead.shutdown()
        router.check_now()
        code, got2 = router.result(rid=b3["request_id"])
        assert code == 200 and got2["replica"] == surv.url
        assert router.stats()["reresolved"] == 1
        # submits fail over to the survivor
        c5, b5, _ = _post(url, {"hs": 4.0, "tp": 9}, token="s3")
        assert c5 == 202 and b5["replica"] == surv.url
        # nothing healthy -> 503 no_healthy_replica + Retry-After
        surv.shutdown()
        router.check_now()
        c6, b6, h6 = _post(url, {"hs": 4.0, "tp": 9}, token="s3")
        assert c6 == 503 and b6["reason"] == "no_healthy_replica"
        assert "Retry-After" in h6
        snap = obs.snapshot()
        series = snap["raft_tpu_serve_router_requests_total"]["series"]
        outcomes = {s["labels"]["outcome"] for s in series}
        assert {"routed", "unauthorized", "quota_exceeded",
                "no_healthy_replica"} <= outcomes
    finally:
        srv.shutdown()
        srv.server_close()
        router.stop()


def test_router_http_trace_propagation_and_metrics():
    """The router hop of the distributed trace, over real HTTP: an
    inbound ``X-Raft-Trace`` is continued as a child span, forwarded
    verbatim to the chosen replica, and echoed in the response body
    and header; a traceless submit mints a fresh root; and ``GET
    /metrics`` serves the Prometheus text exposition."""
    from raft_tpu.obs.tracing import TRACE_HEADER, TraceContext
    a = _StubReplica("A")
    router = ReplicaRouter([a.url], health_interval_s=30.0).start()
    srv = make_server(router, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        inbound = TraceContext.mint()
        req = urllib.request.Request(
            url + "/submit",
            data=json.dumps({"hs": 2, "tp": 9}).encode(),
            method="POST", headers={TRACE_HEADER: inbound.to_header()})
        with urllib.request.urlopen(req, timeout=5) as r:
            body, hdrs = json.loads(r.read()), dict(r.headers)
        tr = body["trace"]
        assert tr["trace_id"] == inbound.trace_id
        assert tr["parent_id"] == inbound.span_id
        assert tr["span_id"] != inbound.span_id
        echoed = TraceContext.parse(hdrs[TRACE_HEADER])
        assert (echoed.trace_id, echoed.span_id) == \
            (inbound.trace_id, tr["span_id"])
        # the replica hop received the SAME continued context
        fwd = TraceContext.parse(a.last_trace)
        assert (fwd.trace_id, fwd.span_id) == \
            (inbound.trace_id, tr["span_id"])
        # no inbound header -> a fresh root (different trace, no parent)
        _, b2, _ = _post(url, {"hs": 2.5, "tp": 9})
        assert b2["trace"]["trace_id"] != inbound.trace_id
        assert not b2["trace"].get("parent_id")
        with urllib.request.urlopen(url + "/metrics", timeout=5) as r:
            text = r.read().decode()
            ctype = r.headers["Content-Type"]
        assert "version=0.0.4" in ctype
        assert "raft_tpu_serve_router_requests_total" in text
    finally:
        srv.shutdown()
        srv.server_close()
        router.stop()
        a.shutdown()


def test_router_submit_failover_midrequest():
    """A replica that accepts the TCP connection but dies mid-request
    is failed over within the same submit (counted)."""
    b = _StubReplica("B")
    router = ReplicaRouter(["http://127.0.0.1:9", b.url],
                           health_interval_s=30.0)
    # both "healthy" as far as the router knows: the dead one is
    # discovered by the submit itself (affinity pins the tenant to the
    # replica that just died — the exact mid-request failover window)
    for bk in router.backends:
        bk.healthy = True
    router._affinity["default"] = "http://127.0.0.1:9"
    code, body, _ = router.submit({"hs": 2.0, "tp": 9.0})
    assert code == 202 and body["replica"] == b.url
    st = router.stats()
    assert st["failovers"] == 1 and st["routed"] == 1
    assert not router.backends[0].healthy
    b.shutdown()


# ---------------------------------------------------------------------------
# unit: trend-store facts + the replication/failover SLO rules
# ---------------------------------------------------------------------------

def test_replication_facts_trend_row_and_slo_rules(tmp_path,
                                                   monkeypatch):
    from raft_tpu.obs import trendstore as T

    # a dead replica's mirror with one completed + one pending request
    rd = wal.request_digest(2.0, 9.0, 0.0)
    j = wal.RequestJournal(str(tmp_path / "mirror"), run_id="dead")
    j.record_admit(0, "req0", rd, 2.0, 9.0, 0.0, 60.0, "default")
    j.record_complete(0, rd, "sha256:d0", "full", 0, [2.0] * 6, 3, True)
    j.record_admit(1, "req1", wal.request_digest(3.0, 9.0, 0.0),
                   3.0, 9.0, 0.0, 60.0, "default")
    j.close()
    monkeypatch.setenv("RAFT_TPU_TREND_DB", str(tmp_path / "t.sqlite"))
    obs.configure(str(tmp_path / "obs"))
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path / "succ" / "journal",
                                   [tmp_path / "succ" / "mirror"]))
    info = svc.recover(str(tmp_path / "mirror"))
    svc.start()
    assert info["tickets"][1].result(10.0).ok
    summary = svc.stop()
    assert summary["failover"] == 1
    assert summary["failover_lost_count"] == 0
    assert summary["replication_lag_records"] == 0
    store = T.TrendStore(str(tmp_path / "t.sqlite"))
    rows = store.rows(kind="serve")
    facts = rows[0]["facts"]
    assert facts["serve_failover"] == 1
    assert facts["serve_failover_lost_count"] == 0
    assert facts["serve_replication_lag_records"] == 0
    assert facts["serve_replication_errors"] == 0
    report = T.evaluate_slo(rows)
    by_name = {r["name"]: r for r in report["results"]}
    assert not by_name["serve_failover_lost_count"]["skipped"]
    assert by_name["serve_failover_lost_count"]["ok"]
    assert not by_name["serve_replication_lag_records"]["skipped"]
    assert by_name["serve_replication_lag_records"]["ok"]
    # a lost request across the boundary MUST fail the gate
    bad = [dict(rows[0]) for _ in range(1)]
    bad[0] = {**rows[0],
              "facts": {**facts, "serve_failover_lost_count": 2}}
    rep2 = T.evaluate_slo(bad)
    assert not rep2["ok"]


def test_bench_serve_open_loop_facts(tmp_path, monkeypatch):
    # bench.py setdefaults RAFT_TPU_X64=0 at import for the TPU path;
    # pin it under monkeypatch so the setdefault cannot leak f32 into
    # the subprocess-spawning tests that follow
    monkeypatch.setenv("RAFT_TPU_X64",
                       os.environ.get("RAFT_TPU_X64", "1"))
    import bench

    monkeypatch.setenv("RAFT_TPU_TREND_DB", str(tmp_path / "t.sqlite"))
    obs.configure(str(tmp_path / "obs"))
    rep = bench.serve_bench(runner_factory=stub_factory,
                            n_requests=16, rps=50.0)
    assert rep["ok"] and rep["completed"] == 16 and rep["shed"] == 0
    assert 0.0 < rep["batch_fill_ratio"] <= 1.0
    assert rep["admission_p99_s"] >= rep["admission_p50_s"] >= 0.0
    assert rep["cases_per_min"] > 0
    # the fleet controller's input signals, measured under this load
    assert rep["queue_depth_p99"] >= rep["queue_depth_p50"] >= 0
    assert rep["quota_pressure"] == 0.0        # nothing shed
    from raft_tpu.obs import trendstore as T
    rows = T.TrendStore(str(tmp_path / "t.sqlite")).rows(
        kind="bench_serve")
    facts = rows[0]["facts"]
    assert facts["serve_cases_per_min"] == rep["cases_per_min"]
    assert facts["serve_batch_fill_ratio"] == rep["batch_fill_ratio"]
    assert facts["serve_admission_p99_s"] == rep["admission_p99_s"]
    assert facts["serve_queue_depth_p99"] == rep["queue_depth_p99"]
    assert facts["serve_quota_pressure"] == rep["quota_pressure"]


# ---------------------------------------------------------------------------
# integration: meshed service digest parity (virtual devices)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cyl_fowt():
    from raft_tpu.serve.soak import build_fowt
    return build_fowt("Vertical_cylinder")


def test_meshed_service_reproduces_unmeshed_digests(cyl_fowt, tmp_path,
                                                    monkeypatch):
    """ISSUE satellite: ``ServeConfig(mesh=...)`` solves a tenant's
    batching window on a sharded mesh and reproduces the unmeshed
    results on virtual devices — iteration counts and convergence
    flags EXACT, responses at the PR 8 partition-parity tolerance
    (XLA SPMD may reassociate reductions by one ulp, exactly as the
    committed MULTICHIP gate records), and meshed digests bit-for-bit
    STABLE across a warm exec-cache restart (the key carries the full
    mesh facts, so warm tenancy composes with sharding)."""
    from raft_tpu.parallel import exec_cache, partition

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path / "c"))
    exec_cache.reset_memo()
    rows = [(1.5, 8.0, 0.0), (2.5, 9.0, 0.5), (3.5, 10.0, 1.0),
            (2.0, 8.5, 0.2)]

    def run(cfg):
        svc = SweepService(cyl_fowt, cfg)
        tickets = [svc.submit(h, t, b) for h, t, b in rows]
        svc.start()
        out = [t.result(300.0) for t in tickets]
        summary = svc.stop()
        assert all(r.ok for r in out)
        return out, summary

    base = dict(queue_max=8, batch_cases=2, window_s=0.02,
                batch_deadline_s=120.0, nIter=4, degrade_after=99)
    plain, _ = run(ServeConfig(**base))
    mesh = partition.make_mesh((2,), ("cases",))
    exec_cache.reset_memo()
    meshed, _ = run(ServeConfig(**base, mesh=mesh))
    for p, m in zip(plain, meshed):
        assert (m.iters, m.converged) == (p.iters, p.converged)
        np.testing.assert_allclose(m.std, p.std, rtol=1e-9, atol=1e-15)
    # warm restart of the MESHED program (exec-cache round trip):
    # digests reproduce bit-for-bit — the determinism the replicated
    # WAL's digest gates rest on
    exec_cache.reset_memo()
    meshed2, summary = run(ServeConfig(**base, mesh=mesh))
    assert [r.digest for r in meshed2] == [r.digest for r in meshed]
    assert summary["exec_cache"]["default/full"] == "hit"
    # the mesh topology rides the manifest config scalars
    assert ServeConfig(**base, mesh=mesh).scalars()["mesh"] == "cases=2"


# ---------------------------------------------------------------------------
# integration: the ISSUE failover acceptance (subprocess, coarse
# cylinder, mirror-only recovery on a fresh "host")
# ---------------------------------------------------------------------------

def test_failover_soak_acceptance(tmp_path, monkeypatch):
    """Child A admits into a mirrored WAL and is SIGKILLed mid-batch;
    successor B boots from ONLY the mirror in a fresh directory tree
    (a different "host"): zero accepted requests lost, every digest
    bit-for-bit equal to an uninterrupted run, warm exec-cache start,
    failover facts clean."""
    from raft_tpu.parallel import exec_cache
    from raft_tpu.serve import soak

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR",
                       str(tmp_path / "cache"))
    exec_cache.reset_memo()
    root = tmp_path / "failover"
    report = soak.run_failover(journal_dir=str(root), n_requests=10,
                               kill_at=6)
    assert report["ok"], {k: report[k] for k in
                          ("killed", "child_rc", "lost",
                           "digest_mismatches", "recover", "failover",
                           "failover_lost_count")}
    assert report["child_rc"] == 137
    # every accepted request reached the mirror BEFORE the kill
    assert report["mirror_admitted"] == report["n_requests"]
    assert 0 < report["pre_kill_completed"] < report["n_requests"]
    rec = report["recover"]
    assert rec["recovered"] == report["pre_kill_completed"]
    assert rec["recovered"] + rec["replayed"] == report["n_requests"]
    assert report["lost"] == [] and report["digest_mismatches"] == []
    assert report["failover"] == 1
    assert report["failover_lost_count"] == 0
    assert report["restart_warm_start"] == 1
    assert report["summary"]["unhandled"] == 0
    # the successor never read the primary: its recovery source was the
    # mirror, and its own journal+mirror now carry the full story
    succ_journal = os.path.join(str(root), "successor", "journal")
    succ = wal.replay(succ_journal)
    assert set(succ["completed"]) | \
        set(wal.replay(os.path.join(str(root), "mirror"))["completed"]) \
        == set(range(report["n_requests"]))
    # -- distributed tracing across the host boundary: every request's
    # trace reassembles fully connected from the WALs alone, and at
    # least one killed-mid-flight request carries the admission(host A)
    # -> resume(host B) link on two distinct process tracks
    tf = report["trace"]
    assert tf["trace_count"] == report["n_requests"]
    assert tf["trace_orphan_spans"] == 0
    assert tf["trace_resume_links"] >= 1
    assert tf["trace_process_tracks"] >= 2
    from raft_tpu.obs import traceview
    dirs = traceview.discover_journal_dirs(str(root))
    resumed_tid = next(
        t for t in traceview.trace_ids(dirs)
        if traceview.assemble(t, dirs)["resume_links"] >= 1)
    asm = traceview.assemble(resumed_tid, dirs)
    assert asm["process_tracks"] >= 2 and asm["orphan_spans"] == 0
    chrome = traceview.chrome_trace(asm)
    names = {e["ph"] for e in chrome["traceEvents"]}
    assert {"M", "X", "s", "f"} <= names       # tracks, spans, arrows
