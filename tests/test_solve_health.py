"""Hot-path solve-health telemetry (``RAFT_TPU_HEALTH=1`` / ``health=True``).

The opt-in health mode makes the batched sweep program additionally
emit per-lane linear-solve residuals, a conditioning proxy, and
non-finite flags — riding the batch's existing single sanctioned
summary pull.  These tests pin the ISSUE acceptance scenario (OC3 at
f64: max relative residual <= 1e-8, zero non-finite lanes, facts
visible in the span, /metrics, the manifest, and a trend row), the
serve-layer provenance plumbing, and the cache-key discipline: with
health OFF the exec-cache key is byte-identical to the uninstrumented
build; health ON forks it.
"""
import json
import os

import numpy as np
import pytest

from raft_tpu import _config, obs
from raft_tpu.parallel import exec_cache
from raft_tpu.parallel.sweep import sweep_cases


@pytest.fixture(scope="module")
def oc3_fowt():
    from raft_tpu.io.designs import load_design
    from raft_tpu.models.fowt import build_fowt

    design = load_design("OC3spar")
    w = np.arange(0.05, 0.45, 0.05) * 2 * np.pi     # 8 coarse bins
    return build_fowt(design, w,
                      depth=float(design["site"]["water_depth"]))


# ---------------------------------------------------------------------------
# config knob
# ---------------------------------------------------------------------------

def test_health_knob(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_HEALTH", raising=False)
    assert _config.health_enabled() is False         # off by default
    monkeypatch.setenv("RAFT_TPU_HEALTH", "1")
    assert _config.health_enabled() is True
    monkeypatch.setenv("RAFT_TPU_HEALTH", "off")
    assert _config.health_enabled() is False
    monkeypatch.setenv("RAFT_TPU_HEALTH", "on")
    assert _config.health_enabled() is True
    try:
        _config.set_health_mode("0")                 # override beats env
        assert _config.health_enabled() is False
        with pytest.raises(ValueError):
            _config.set_health_mode("maybe")
    finally:
        _config.set_health_mode(None)


# ---------------------------------------------------------------------------
# the acceptance scenario: OC3 at f64
# ---------------------------------------------------------------------------

def test_oc3_health_sweep_residual_and_surfaces(oc3_fowt, tmp_path):
    obs.configure(str(tmp_path))
    ncases = 4
    Hs = np.array([2.0, 4.0, 6.0, 8.0])
    Tp = np.array([8.0, 10.0, 12.0, 14.0])
    beta = np.zeros(ncases)
    out = sweep_cases(oc3_fowt, Hs, Tp, beta, nIter=4, health=True)

    # on-device health lanes ride the batch output, unpadded
    res = np.asarray(out["health_residual"])
    cond = np.asarray(out["health_cond"])
    assert res.shape == (ncases,) and cond.shape == (ncases,)
    assert np.all(np.isfinite(res))
    assert float(res.max()) <= 1e-8                  # f64 linear solve
    assert np.all(np.isfinite(cond)) and np.all(cond >= 1.0)
    # health must not perturb the physics outputs
    assert np.all(np.isfinite(np.asarray(out["std"])))

    # /metrics surface
    snap = obs.snapshot()
    series = {(s["labels"].get("phase"), s["labels"].get("stat")):
              s["value"]
              for s in snap["raft_tpu_solve_residual_rel"]["series"]}
    assert series[("sweep", "max")] <= 1e-8
    assert series[("sweep", "median")] <= series[("sweep", "max")]
    nonfin = {s["labels"]["phase"]: s["value"]
              for s in snap["raft_tpu_solve_nonfinite_lanes"]["series"]}
    assert nonfin["sweep"] == 0.0
    assert "raft_tpu_solve_condition_max" in snap
    assert "raft_tpu_solve_drag_iters_max" in snap

    # span surface
    sweep_span = [e for e in obs.spans() if e["name"] == "sweep_cases"][-1]
    assert sweep_span["attrs"]["health_residual_max"] <= 1e-8
    assert sweep_span["attrs"]["health_nonfinite"] == 0

    # manifest + trend-row surface (facts_from_manifest extraction)
    man_paths = [p for p in os.listdir(tmp_path)
                 if p.endswith(".manifest.json")]
    assert len(man_paths) == 1
    with open(tmp_path / man_paths[0]) as f:
        man = json.load(f)
    hinfo = man["extra"]["solve_health"]
    assert hinfo["residual_rel_max"] <= 1e-8
    assert hinfo["nonfinite_lanes"] == 0
    assert hinfo["lanes"] == ncases
    json.dumps(hinfo, allow_nan=False)               # JSON-safe always
    rows = obs.trendstore.TrendStore(
        str(tmp_path / "trend.sqlite")).rows()
    assert len(rows) == 1
    facts = rows[0]["facts"]
    assert facts["solve_residual_rel_max"] <= 1e-8
    assert facts["solve_nonfinite_lanes"] == 0

    # flight-recorder surface: the solve_health event names a worst lane
    ev_paths = [p for p in os.listdir(tmp_path)
                if p.endswith(".events.jsonl")]
    events = [json.loads(line)
              for line in open(tmp_path / ev_paths[0])]
    (hev,) = [e for e in events if e.get("type") == "solve_health"]
    assert hev["phase"] == "sweep" and 0 <= hev["worst_lane"] < ncases

    # the new SLO rules hold over this run's trend row
    rep = obs.trendstore.evaluate_slo(rows)
    by_name = {r["name"]: r for r in rep["results"]}
    assert by_name["solve_nonfinite_lanes"]["ok"]
    assert not by_name["solve_nonfinite_lanes"]["skipped"]
    assert by_name["solve_residual_rel_max"]["ok"]


def test_health_off_is_the_default(oc3_fowt):
    out = sweep_cases(oc3_fowt, np.array([3.0]), np.array([9.0]),
                      np.array([0.0]), nIter=2)
    assert "health_residual" not in out
    assert "health_cond" not in out


# ---------------------------------------------------------------------------
# cache-key discipline
# ---------------------------------------------------------------------------

def test_health_forks_the_exec_cache_key():
    base = exec_cache.make_key(fn="sweep_cases", ncases=4, nw=8)
    # health OFF adds NO fact: the default key is byte-identical to the
    # uninstrumented build's (golden ledgers and warm caches carry over)
    assert exec_cache.make_key(fn="sweep_cases", ncases=4, nw=8,
                               **({})) == base
    assert exec_cache.make_key(fn="sweep_cases", ncases=4, nw=8,
                               health=True) != base


def test_batch_runner_health_key_fork(oc3_fowt, tmp_path, monkeypatch):
    from raft_tpu.parallel.sweep import make_batch_runner

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE_DIR", str(tmp_path))
    exec_cache.reset_memo()
    r1 = make_batch_runner(oc3_fowt, 2, nIter=2)
    assert r1.cache_state == "miss" and r1.health is False
    r2 = make_batch_runner(oc3_fowt, 2, nIter=2, health=True)
    assert r2.cache_state == "miss" and r2.health is True   # forked key
    r3 = make_batch_runner(oc3_fowt, 2, nIter=2)
    assert r3.cache_state == "hit"          # default key undisturbed
    out = r2(np.array([2.0, 4.0]), np.array([8.0, 10.0]),
             np.array([0.0, 0.3]))
    res = np.asarray(out["health_residual"])
    assert res.shape == (2,) and float(res.max()) <= 1e-8
    ref = r3(np.array([2.0, 4.0]), np.array([8.0, 10.0]),
             np.array([0.0, 0.3]))
    # identical physics from the health-on program, bit for bit
    np.testing.assert_array_equal(np.asarray(out["std"]),
                                  np.asarray(ref["std"]))


# ---------------------------------------------------------------------------
# serve-layer provenance
# ---------------------------------------------------------------------------

def test_serve_result_provenance_carries_health(monkeypatch):
    from raft_tpu.io.designs import load_design
    from raft_tpu.models.fowt import build_fowt
    from raft_tpu.serve import ServeConfig, SweepService

    monkeypatch.setenv("RAFT_TPU_HEALTH", "1")
    design = load_design("Vertical_cylinder")
    w = np.arange(0.05, 0.5, 0.05) * 2 * np.pi
    fowt = build_fowt(design, w,
                      depth=float(design["site"]["water_depth"]))
    cfg = ServeConfig(queue_max=8, batch_cases=2, window_s=0.02,
                      batch_deadline_s=60.0)
    svc = SweepService(fowt, cfg)
    svc.start()
    try:
        t1 = svc.submit(2.0, 8.0, 0.0)
        t2 = svc.submit(3.0, 9.0, 0.2)
        r1 = t1.result(120.0)
        r2 = t2.result(120.0)
    finally:
        svc.stop()
    for r in (r1, r2):
        h = (r.extra or {}).get("provenance", {}).get("solve_health")
        assert h is not None
        assert h["residual_rel"] is not None and h["residual_rel"] <= 1e-6
        assert h["batch_nonfinite_lanes"] == 0
        json.dumps(h, allow_nan=False)
    # the health facts must NOT move the physics digest: digests are
    # computed from the response spectra alone
    assert r1.digest != r2.digest
