"""The learned read tier (raft_tpu/serve/surrogate.py + models/
surrogate_net.py): offline distillation from the result store,
calibrated serving gates, the in-service surrogate slot, the audited
escalation ladder, and the trend-store facts that gate it in CI.

Everything here runs on stub physics — a smooth closed-form std map
shared by the corpus builder and the batch-engine stub, so audits
compare the surrogate against the same ground truth it was distilled
from.  No real solves, no TPU.
"""
import json
import os
import time

import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.models import surrogate_net
from raft_tpu.obs.ledger import digest_metrics
from raft_tpu.serve import ServeConfig, SweepService, surrogate
from raft_tpu.serve import journal as wal
from raft_tpu.serve.resultstore import ResultStore
from raft_tpu.serve.surrogate import SurrogateBundle, SurrogateTier

pytestmark = pytest.mark.filterwarnings(
    "ignore::DeprecationWarning")

# the shared ground truth: smooth on the (Hs, Tp, beta) scales the
# case tables use, every channel's magnitude comfortably off zero so
# the 1%-of-mean relative floor never dominates the calibration
ITERS = 4


def _smooth_std(h, t, b):
    return [0.12 * h, 0.05 * h + 0.02 * t, 0.01 * t + 0.2,
            0.3 + 0.002 * h * t, 0.08 * h + 0.1, 0.25 + 0.02 * t
            + 0.05 * b]


def _grid():
    """The training corpus grid: 6 x 6 over (Hs, Tp), beta fixed —
    36 rows, comfortably above the distill floor."""
    rows = []
    for h in np.linspace(1.5, 5.0, 6):
        for t in np.linspace(6.0, 12.0, 6):
            rows.append((float(h), float(t), 0.0))
    return rows


def _put_row(store, h, t, b, tenant="default"):
    std = _smooth_std(h, t, b)
    doc = {"rdigest": wal.request_digest(h, t, b, tenant),
           "digest": digest_metrics({"std": std, "iters": ITERS,
                                     "converged": True}),
           "std": std, "iters": ITERS, "converged": True,
           "tenant": tenant, "Hs": h, "Tp": t, "beta": b}
    assert store.put(doc)
    return doc


def _seed_store(store_dir):
    store = ResultStore(store_dir)
    for h, t, b in _grid():
        _put_row(store, h, t, b)
    return store


def stub_factory(mode, fowt, ncases, **kw):
    """Batch engine speaking the shared ground truth."""
    def run(Hs, Tp, beta):
        Hs, Tp, beta = (np.asarray(a) for a in (Hs, Tp, beta))
        return {"std": np.stack([_smooth_std(h, t, b) for h, t, b
                                 in zip(Hs, Tp, beta)]),
                "iters": np.full(len(Hs), ITERS),
                "converged": np.ones(len(Hs), bool)}
    run.ncases = ncases
    run.cache_state = "stub"
    return run


def _cfg(tmp_path, sur_dir, **kw):
    base = dict(queue_max=16, batch_cases=4, window_s=0.02,
                batch_deadline_s=10.0, retry_base_s=0.01,
                degrade_after=99, store_dir=str(tmp_path / "store"),
                surrogate_dir=str(sur_dir), surrogate_tol=0.05)
    base.update(kw)
    return ServeConfig(**base)


@pytest.fixture(scope="module")
def distilled(tmp_path_factory):
    """One seeded store + one distilled bundle, shared by the
    read-only tests (training dominates this module's runtime)."""
    root = tmp_path_factory.mktemp("surrogate")
    store_dir = str(root / "store")
    sur_dir = str(root / "sur")
    store = _seed_store(store_dir)
    info = surrogate.distill(store, sur_dir, steps=900, seed=3)
    return {"root": root, "store_dir": store_dir, "sur_dir": sur_dir,
            "info": info}


# ---------------------------------------------------------------------------
# the net and the calibration primitive
# ---------------------------------------------------------------------------

def test_surrogate_net_fit_and_predict_shapes():
    rng = np.random.default_rng(0)
    X = rng.uniform([1, 6, -0.3], [5, 12, 0.3], size=(64, 3))
    Y = np.stack([[*_smooth_std(*x), ITERS, 4.0] for x in X])
    params, info = surrogate_net.fit(X, Y, hidden=(16, 16), steps=400,
                                     lr=5e-3, seed=0)
    assert info["loss_last"] < info["loss_first"]
    pred = np.asarray(surrogate_net.forward(params, X))
    assert pred.shape == (64, surrogate_net.OUT_CHANNELS)
    # the fit is close on its own training support
    assert float(np.abs(pred[:, :6] - Y[:, :6]).mean()) < 0.1
    # params serialize as plain float64 numpy (the bundle contract);
    # "layers" is the integer topology record
    assert all(np.asarray(v).dtype == np.float64
               for k, v in params.items() if k != "layers")
    assert np.issubdtype(np.asarray(params["layers"]).dtype,
                         np.integer)


def test_conformal_bound_is_the_order_statistic():
    # alpha=0.1, n=9 -> k = ceil(10 * 0.9) = 9 -> the 9th smallest
    # (here: the max); alpha=0.5 -> k=5 -> the median
    err = np.arange(1.0, 10.0).reshape(9, 1)
    assert surrogate._conformal_bound(err, 0.1)[0] == 9.0
    assert surrogate._conformal_bound(err, 0.5)[0] == 5.0
    # per-channel, not pooled
    err2 = np.stack([np.arange(1.0, 10.0),
                     np.arange(10.0, 100.0, 10.0)], axis=1)
    assert list(surrogate._conformal_bound(err2, 0.1)) == [9.0, 90.0]


# ---------------------------------------------------------------------------
# distill -> publish -> load
# ---------------------------------------------------------------------------

def test_distill_publishes_versioned_verified_bundle(distilled):
    info = distilled["info"]
    assert info["version"] == 1
    assert info["corpus_rows"] == 36
    assert info["counts"]["exported"] == 36
    assert info["corpus_digest"].startswith("sha256:")
    # the calibrated bound clears the default serving tolerance —
    # smooth physics, well-conditioned channels
    assert info["bound_rel_max"] <= 0.05, info
    bundle = SurrogateBundle.load(distilled["sur_dir"], "default")
    assert bundle is not None
    assert bundle.digest == info["digest"]
    assert bundle.version == 1
    assert bundle.serving_ok(0.05)
    assert bundle.meta["corpus_digest"] == info["corpus_digest"]
    # prediction parity with the training physics, inside the hull
    std, iters, converged = bundle.predict(3.1, 9.2, 0.0)
    want = _smooth_std(3.1, 9.2, 0.0)
    assert converged and iters >= 0
    np.testing.assert_allclose(std, want, rtol=0.08, atol=0.05)
    assert bundle.in_hull(3.1, 9.2, 0.0)
    assert not bundle.in_hull(9.0, 9.2, 0.0)      # off the Hs support
    # the audit comparator passes the true physics at the bound
    cold = type("C", (), {"std": want, "iters": ITERS,
                          "converged": True})
    ok, detail = bundle.within_bound(std, iters, converged, cold)
    assert ok, detail


def test_distill_dead_channels_do_not_veto_serving(tmp_path):
    """Real axisymmetric physics under beta=0 seas: sway/roll/yaw std
    sit at ~1e-18 while surge is O(0.5 m).  The net's y_sd floor puts
    its reconstruction noise on a dead channel near 1e-8 — against the
    channel's own near-zero mean that is a relative error of ~1e4, and
    the old per-channel-only floor let it veto serving for the whole
    tenant (bound_rel_max ~300 on the Vertical_cylinder bench).  The
    scale-aware rel_floor measures a dead DOF against the platform's
    dominant response instead, and the audit comparator honours the
    same floored-relative contract."""
    store = ResultStore(str(tmp_path / "store"))
    for h, t, b in _grid():
        live = _smooth_std(h, t, b)
        std = [live[0], 1e-18, live[2], 1e-18, live[4], 1e-18]
        doc = {"rdigest": wal.request_digest(h, t, b, "default"),
               "digest": digest_metrics({"std": std, "iters": ITERS,
                                         "converged": True}),
               "std": std, "iters": ITERS, "converged": True,
               "tenant": "default", "Hs": h, "Tp": t, "beta": b}
        assert store.put(doc)
    sur = str(tmp_path / "sur")
    info = surrogate.distill(store, sur, steps=900, seed=3)
    # the dead channels no longer blow the serving gate
    assert info["bound_rel_max"] <= 0.05, info
    bundle = SurrogateBundle.load(sur, "default")
    assert bundle.serving_ok(0.05)
    # the floor rides in the bundle: dead channels floored by the
    # dominant channel's scale, live channels by their own mean
    assert bundle.rel_floor.shape == (6,)
    assert float(bundle.rel_floor[1]) >= 1e-4   # scale-aware, not 1e-12
    # the audit passes true physics whose dead channels are exact zero
    # even though the net predicts O(1e-8) noise there...
    std, iters, converged = bundle.predict(3.1, 9.2, 0.0)
    want = _smooth_std(3.1, 9.2, 0.0)
    cold = type("C", (), {"std": [want[0], 0.0, want[2], 0.0,
                                  want[4], 0.0],
                          "iters": ITERS, "converged": True})
    ok, detail = bundle.within_bound(std, iters, converged, cold)
    assert ok, detail
    # ...while a genuinely wrong live channel still trips it
    bad = list(std)
    bad[0] = float(cold.std[0]) * 1.5
    ok, detail = bundle.within_bound(bad, iters, converged, cold)
    assert not ok
    assert detail["worst_std_err_over_bound"] > 1.0


def test_distill_too_small_corpus_is_typed(tmp_path):
    store = ResultStore(str(tmp_path / "store"))
    for h, t, b in _grid()[:6]:
        _put_row(store, h, t, b)
    with pytest.raises(errors.ModelConfigError):
        surrogate.distill(store, str(tmp_path / "sur"), steps=10)


def test_redistill_bumps_version_and_clears_quarantine(tmp_path):
    store = _seed_store(str(tmp_path / "store"))
    sur = str(tmp_path / "sur")
    v1 = surrogate.distill(store, sur, steps=60, seed=1)
    assert v1["version"] == 1
    marker = surrogate.quarantine_marker_path(sur, "default")
    with open(marker, "w") as f:
        json.dump({"reason": "test"}, f)
    v2 = surrogate.distill(store, sur, steps=60, seed=1)
    assert v2["version"] == 2
    assert not os.path.exists(marker)      # fresh publish supersedes
    assert SurrogateBundle.load(sur, "default").version == 2


def test_bundle_corruption_ladder_is_typed(tmp_path, distilled):
    import shutil

    sur = str(tmp_path / "sur")
    shutil.copytree(distilled["sur_dir"], sur)
    pointer = surrogate.bundle_pointer_path(sur, "default")
    # flipped bytes in the bundle file -> digest mismatch
    with open(pointer, encoding="utf-8") as f:
        name = json.load(f)["file"]
    path = os.path.join(sur, name)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(errors.CacheCorruption):
        SurrogateBundle.load(sur, "default")
    # unparseable pointer
    with open(pointer, "w") as f:
        f.write("{not json")
    with pytest.raises(errors.CacheCorruption):
        SurrogateBundle.load(sur, "default")
    # pointer at a missing file
    with open(pointer, "w") as f:
        json.dump({"file": "gone.npz", "sha256": "sha256:0",
                   "version": 9}, f)
    with pytest.raises(errors.CacheCorruption):
        SurrogateBundle.load(sur, "default")
    # no pointer at all is a plain miss, not an error
    os.unlink(pointer)
    assert SurrogateBundle.load(sur, "default") is None
    # the tier converts the typed failure into a counted exact-serving
    # miss — corruption must never take down admission
    with open(pointer, "w") as f:
        f.write("{not json")
    tier = SurrogateTier(sur, tol=0.05, audit_every=8,
                         refresh_writes=64)
    assert tier.lookup("default") is None
    assert tier.facts()["load_errors"] == 1


# ---------------------------------------------------------------------------
# the tier: serving gates, audit cadence, quarantine
# ---------------------------------------------------------------------------

def test_tier_decide_gates_and_audit_cadence(distilled):
    tier = SurrogateTier(distilled["sur_dir"], tol=0.05, audit_every=3,
                         refresh_writes=10)
    hit = tier.decide("default", 3.0, 9.0, 0.0)
    assert hit is not None
    bundle, (std, iters, converged) = hit
    assert converged and len(std) == 6
    # out-of-hull escalates
    assert tier.decide("default", 9.0, 9.0, 0.0) is None
    # a tolerance tighter than the calibrated bound never serves
    strict = SurrogateTier(distilled["sur_dir"], tol=1e-6,
                           audit_every=3, refresh_writes=10)
    assert strict.decide("default", 3.0, 9.0, 0.0) is None
    # an unknown tenant has no bundle
    assert tier.decide("acme", 3.0, 9.0, 0.0) is None
    assert not tier.has_bundle("acme")
    # cadence: every 3rd serve is audit-due...
    assert [tier.note_served("default", 0) for _ in range(6)] \
        == [False, False, True, False, False, True]
    # ...and the drift trigger fires when the store has grown by
    # refresh_writes puts since the last audit, off-cadence
    assert tier.note_served("default", 10)    # 7th serve, 10 puts
    assert not tier.note_served("default", 12)


def test_tier_quarantine_is_durable_until_redistill(tmp_path):
    store = _seed_store(str(tmp_path / "store"))
    sur = str(tmp_path / "sur")
    surrogate.distill(store, sur, steps=900, seed=3)
    tier = SurrogateTier(sur, tol=0.05, audit_every=8,
                         refresh_writes=64)
    bundle = tier.lookup("default")
    assert tier.decide("default", 3.0, 9.0, 0.0) is not None
    tier.quarantine("default", bundle, "bound_violation",
                    {"worst_std_err_over_bound": 9.9})
    tier.quarantine("default", bundle, "bound_violation")  # idempotent
    assert tier.quarantined("default")
    assert tier.decide("default", 3.0, 9.0, 0.0) is None
    assert "default" in tier.facts()["quarantined"]
    # durable: a fresh tier (a restarted service, a sibling replica)
    # sees the marker and keeps serving exact
    tier2 = SurrogateTier(sur, tol=0.05, audit_every=8,
                          refresh_writes=64)
    assert tier2.lookup("default") is None
    assert tier2.decide("default", 3.0, 9.0, 0.0) is None
    # a fresh distill clears the marker; reload() brings it live
    surrogate.distill(store, sur, steps=900, seed=3)
    tier2.reload("default")
    assert tier2.decide("default", 3.0, 9.0, 0.0) is not None
    assert tier2.lookup("default").version == 2


# ---------------------------------------------------------------------------
# the service: the surrogate slot, provenance, WAL, audit, quarantine
# ---------------------------------------------------------------------------

def test_service_serves_in_hull_and_escalates(tmp_path, distilled):
    import shutil

    shutil.copytree(distilled["store_dir"], str(tmp_path / "store"))
    cfg = _cfg(tmp_path, distilled["sur_dir"],
               journal_dir=str(tmp_path / "wal"),
               surrogate_audit_every=10 ** 6)
    svc = SweepService(runner_factory=stub_factory, config=cfg)
    svc.start()
    try:
        # an in-hull exact-digest MISS answers from the bundle:
        # immediately, no queue slot, full provenance
        t = svc.submit(2.2, 8.3, 0.0)
        assert t.done()                      # no batch window wait
        r = t.result(10.0)
        assert r.ok and r.source == "surrogate"
        assert r.seq == -1 and r.attempts == 0
        np.testing.assert_allclose(r.std, _smooth_std(2.2, 8.3, 0.0),
                                   rtol=0.08, atol=0.05)
        prov = r.extra["provenance"]["surrogate"]
        assert prov["bundle"] == distilled["info"]["digest"]
        assert prov["tol"] == 0.05
        assert r.digest == digest_metrics(
            {"std": [float(v) for v in r.std], "iters": int(r.iters),
             "converged": bool(r.converged)})
        # out-of-hull escalates to a real solve
        r2 = svc.submit(8.5, 9.0, 0.0).result(30.0)
        assert r2.ok and r2.source != "surrogate"
        # exact=True bypasses the tier even in-hull
        r3 = svc.submit(2.4, 8.1, 0.0, exact=True).result(30.0)
        assert r3.ok and r3.source != "surrogate"
        # an exact-digest store hit STILL wins over the surrogate
        row = _grid()[0]
        r4 = svc.submit(*row).result(10.0)
        assert r4.ok and r4.source == "cached"
    finally:
        summary = svc.stop()
    assert summary["surrogate_served"] == 1
    assert summary["surrogate_escalated"] == 1
    assert summary["surrogate_bound_violation_served_count"] == 0
    assert summary["surrogate_quarantine_miss"] == 0
    assert summary["surrogate_read_p50_ms"] is not None
    assert 0.0 < summary["surrogate_hit_ratio"] < 1.0
    assert summary["surrogate"]["bundles"]["default"]["version"] == 1
    # the WAL carries the provenance record — non-terminal, seq-less,
    # and deliberately NOT a complete: replay must never mistake
    # predicted physics for a solver result
    rep = wal.replay(cfg.journal_dir)
    assert len(rep["surrogates"]) == 1
    rec = rep["surrogates"][0]
    assert rec["bundle"] == distilled["info"]["digest"]
    assert rec["digest"] == r.digest and rec["audited"] is False
    assert rep["pending"] == []              # nothing re-admits


def test_service_audit_violation_quarantines_then_exact(tmp_path):
    store_dir = str(tmp_path / "store")
    sur = str(tmp_path / "sur")
    store = _seed_store(store_dir)
    # a deliberately stale bundle: self-consistently calibrated on
    # 1.3x-scaled targets, so it SERVES — and every answer violates
    # the true physics at the bound
    surrogate.distill(store, sur, steps=900, seed=3, stale_y_scale=1.3)
    cfg = _cfg(tmp_path, sur, surrogate_audit_every=1)
    svc = SweepService(runner_factory=stub_factory, config=cfg)
    svc.start()
    try:
        q = (2.7, 8.9, 0.0)
        r = svc.submit(*q).result(10.0)
        assert r.ok and r.source == "surrogate"
        assert r.extra["provenance"]["surrogate"]["audited"] is True
        deadline = time.monotonic() + 60.0
        while (svc.stats()["surrogate_quarantines"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        st = svc.stats()
        assert st["surrogate_audits"] == 1
        assert st["surrogate_violations"] == 1
        assert st["surrogate_quarantines"] == 1
        # the tenant is back on exact serving: same request, solver
        # path, digest bit-for-bit with the audit's cold solve
        r_after = svc.submit(*q).result(30.0)
        assert r_after.ok and r_after.source != "surrogate"
        np.testing.assert_allclose(r_after.std, _smooth_std(*q),
                                   rtol=1e-6)
        summary = svc.stop()
    finally:
        svc.stop()
    assert summary["surrogate_bound_violation_served_count"] == 1
    assert summary["surrogate_quarantines"] == 1
    assert summary["surrogate_quarantine_miss"] == 0   # caught, never missed
    # the quarantine is durable: a successor service serves exact
    svc2 = SweepService(runner_factory=stub_factory,
                        config=_cfg(tmp_path, sur))
    svc2.start()
    try:
        r2 = svc2.submit(3.3, 10.1, 0.0).result(30.0)
        assert r2.ok and r2.source != "surrogate"
    finally:
        svc2.stop()


def test_drill_service_scopes_served_violation_fact(tmp_path):
    """cfg.surrogate_drill: the quarantine drill's INTENTIONAL served
    violation reports as ``surrogate_drill_violations`` — the
    zero-tolerance ``surrogate_bound_violation_served_count`` fact
    never appears on a drill row, so the drill can't trip the
    production SLO rule — while ``surrogate_quarantine_miss`` stays
    zero-tolerance (a drill violation the audit fails to quarantine
    is still a silent-audit failure)."""
    from raft_tpu.obs import trendstore

    store_dir = str(tmp_path / "store")
    sur = str(tmp_path / "sur")
    store = _seed_store(store_dir)
    surrogate.distill(store, sur, steps=900, seed=3, stale_y_scale=1.3)
    cfg = _cfg(tmp_path, sur, surrogate_audit_every=1,
               surrogate_drill=True)
    svc = SweepService(runner_factory=stub_factory, config=cfg)
    svc.start()
    try:
        r = svc.submit(2.7, 8.9, 0.0).result(10.0)
        assert r.ok and r.source == "surrogate"
        deadline = time.monotonic() + 60.0
        while (svc.stats()["surrogate_quarantines"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        summary = svc.stop()
    finally:
        svc.stop()
    assert summary["surrogate_drill"] == 1
    assert summary["surrogate_drill_violations"] == 1
    assert "surrogate_bound_violation_served_count" not in summary
    assert summary["surrogate_quarantines"] == 1
    assert summary["surrogate_quarantine_miss"] == 0
    # through fact extraction + the SLO gate: the drill row trends
    # under its own names and passes the zero-tolerance rules
    doc = {"schema": "raft_tpu.run_manifest/v1", "run_id": "drill",
           "kind": "serve", "status": "ok",
           "extra": {"serve": summary}}
    facts = trendstore.facts_from_manifest(doc)
    assert facts["surrogate_drill_violations"] == 1
    assert "surrogate_bound_violation_served_count" not in facts
    rows = [{"kind": "serve", "status": "ok", "facts": facts}]
    assert trendstore.evaluate_slo(rows)["ok"]


# ---------------------------------------------------------------------------
# trend-store facts and the CI gate
# ---------------------------------------------------------------------------

def test_surrogate_facts_reach_trend_row_and_slo_rules():
    from raft_tpu.obs import trendstore

    summary = {"requests": 10, "surrogate_served": 6,
               "surrogate_escalated": 1, "surrogate_audits": 2,
               "surrogate_audit_errors": 0,
               "surrogate_bound_violation_served_count": 0,
               "surrogate_quarantines": 0,
               "surrogate_quarantine_miss": 0,
               "surrogate_hit_ratio": 0.6,
               "surrogate_read_p50_ms": 0.7,
               "surrogate_read_p99_ms": 2.0}
    doc = {"schema": "raft_tpu.run_manifest/v1", "run_id": "t1",
           "kind": "serve", "status": "ok",
           "extra": {"serve": summary}}
    facts = trendstore.facts_from_manifest(doc)
    assert facts["surrogate_served"] == 6
    assert facts["surrogate_bound_violation_served_count"] == 0
    assert facts["surrogate_quarantine_miss"] == 0
    # the bench fact block lands under surrogate_-prefixed names plus
    # the two unprefixed rule-named facts
    bench_doc = {"schema": "raft_tpu.run_manifest/v1", "run_id": "t2",
                 "kind": "bench_surrogate", "status": "ok",
                 "extra": {"surrogate_bench": {
                     "served": 12, "hit_ratio": 0.8,
                     "speedup_vs_cold": 90.0, "read_p50_ms": 0.7,
                     "surrogate_bound_violation_served_count": 0,
                     "surrogate_quarantine_miss": 0}}}
    bfacts = trendstore.facts_from_manifest(bench_doc)
    assert bfacts["surrogate_speedup_vs_cold"] == 90.0
    assert bfacts["surrogate_bound_violation_served_count"] == 0
    names = [r["name"] for r in trendstore.DEFAULT_SLO_RULES]
    assert "surrogate_bound_violation_served_count" in names
    assert "surrogate_quarantine_miss" in names
    rows = [{"kind": "serve", "status": "ok", "facts": facts},
            {"kind": "bench_surrogate", "status": "ok",
             "facts": bfacts}]
    assert trendstore.evaluate_slo(rows)["ok"]
    # zero tolerance: ONE served violation anywhere in the window
    # fails the gate; a missed quarantine fails the second rule
    bad = [{"kind": "bench_surrogate", "status": "ok",
            "facts": {"surrogate_bound_violation_served_count": 1,
                      "surrogate_quarantine_miss": 1}}]
    rep = trendstore.evaluate_slo(bad)
    assert not rep["ok"]
    failing = {r["name"] for r in rep["results"] if not r["ok"]}
    assert {"surrogate_bound_violation_served_count",
            "surrogate_quarantine_miss"} <= failing
    # rows with no surrogate facts (an ordinary serve run) never trip
    # the rule — facts are only emitted on surrogate rows
    plain = [{"kind": "serve", "status": "ok",
              "facts": {"serve_store_hits": 3}}]
    assert trendstore.evaluate_slo(plain)["ok"]


def test_obsctl_tail_renders_surrogate_events(tmp_path):
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    events = tmp_path / "serve_sur.events.jsonl"
    with open(events, "w") as f:
        for e in ({"type": "surrogate_served", "t": 1.0,
                   "rdigest": "sha256:aaaa", "tenant": "default",
                   "bundle": "sha256:bbbb", "version": 2,
                   "audit": True},
                  {"type": "surrogate_audit", "t": 2.0,
                   "rdigest": "sha256:aaaa", "tenant": "default",
                   "ok": False, "worst_std_err_over_bound": 3.25},
                  {"type": "surrogate_quarantine", "t": 3.0,
                   "tenant": "default", "bundle": "sha256:bbbb",
                   "version": 2}):
            f.write(json.dumps(e) + "\n")
    p = subprocess.run(
        [sys.executable, "tools/obsctl.py", "tail", str(events)],
        cwd=repo, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stderr
    lines = p.stdout.splitlines()
    assert any("surrogate served" in ln and "AUDIT-DUE" in ln
               and "bundle v2" in ln for ln in lines)
    assert any("surrogate audit VIOLATION" in ln
               and "worst err/bound 3.25" in ln for ln in lines)
    assert any("SURROGATE QUARANTINE tenant default" in ln
               and "exact serving until re-distill" in ln
               for ln in lines)
