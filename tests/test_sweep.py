"""Mesh-sharded case sweep: sharded outputs must match single-device.

conftest.py forces an 8-virtual-device CPU platform, so these tests
exercise the real `jax.sharding.Mesh` + NamedSharding path of
`sweep_cases` — the framework's ICI/DCN-parallel axis (SURVEY.md §2.9) —
without TPU hardware.
"""
import os

import numpy as np
import pytest
import yaml
from numpy.testing import assert_allclose

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu.models.fowt import build_fowt
from raft_tpu.parallel.sweep import sweep_cases

YAML = "/root/reference/designs/OC3spar.yaml"


@pytest.fixture(scope="module")
def fowt():
    if not os.path.isfile(YAML):
        pytest.skip("reference designs not available")
    design = yaml.safe_load(open(YAML))
    # coarse frequency grid keeps the compile cheap while still exercising
    # the full batched pipeline
    w = np.arange(0.02, 0.40, 0.02) * 2 * np.pi
    depth = float(design["site"]["water_depth"])
    return build_fowt(design, w, depth=depth)


def test_virtual_device_count():
    assert len(jax.devices("cpu")) >= 8


def test_sharded_sweep_matches_single_device(fowt):
    rng = np.random.default_rng(7)
    ncases = 16
    Hs = 4.0 + 2.0 * rng.random(ncases)
    Tp = 8.0 + 6.0 * rng.random(ncases)
    beta = np.deg2rad(rng.integers(0, 360, ncases).astype(float))

    plain = sweep_cases(fowt, Hs, Tp, beta, mesh=None, nIter=4)

    devices = np.array(jax.devices("cpu")[:8])
    mesh = Mesh(devices, axis_names=("cases",))
    sharded = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=4)

    std_p = np.asarray(plain["std"])
    std_s = np.asarray(sharded["std"])
    assert std_s.shape == (ncases, 6)
    assert np.all(np.isfinite(std_s))
    assert_allclose(std_s, std_p, rtol=1e-10, atol=1e-12)
    assert_allclose(np.asarray(sharded["Xi"]), np.asarray(plain["Xi"]),
                    rtol=1e-9, atol=1e-12)


def test_sharded_output_is_distributed(fowt):
    """The case axis must actually be sharded over the mesh devices."""
    ncases = 8
    Hs = np.full(ncases, 6.0)
    Tp = np.full(ncases, 10.0)
    beta = np.zeros(ncases)
    devices = np.array(jax.devices("cpu")[:8])
    mesh = Mesh(devices, axis_names=("cases",))
    out = sweep_cases(fowt, Hs, Tp, beta, mesh=mesh, nIter=2)
    sh = out["std"].sharding
    assert len(sh.device_set) == 8


def test_case_solver_batched_matches_serial(fowt):
    """solver.batched (the hand-batched fixed point used by sweep_cases on
    TPU) must reproduce the serial per-case while_loop solver exactly,
    including per-case convergence freezing."""
    import jax

    from raft_tpu.parallel.sweep import make_case_solver

    solver = make_case_solver(fowt, nIter=6, tol=0.01)
    Hs = jnp.asarray([2.0, 5.0, 8.0, 11.0])
    Tp = jnp.asarray([7.0, 10.0, 12.0, 15.0])
    beta = jnp.deg2rad(jnp.asarray([0.0, 30.0, 120.0, 250.0]))
    out_b = solver.batched(Hs, Tp, beta)
    for i in range(4):
        out_i = solver(Hs[i], Tp[i], beta[i])
        np.testing.assert_allclose(np.asarray(out_b["Xi"][i]),
                                   np.asarray(out_i["Xi"]),
                                   rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(np.asarray(out_b["std"][i]),
                                   np.asarray(out_i["std"]),
                                   rtol=1e-9, atol=1e-12)
