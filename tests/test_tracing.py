"""End-to-end distributed request tracing (obs/tracing.TraceContext +
serve propagation + obs/traceview assembly + obsctl trace).

Unit tier: W3C-style header parse/mint/child semantics, WAL record
round-trip through a stub service (admit/batch/complete all carry the
context, delivered results carry ``provenance["trace"]``), the
per-request phase-breakdown histograms + summary percentiles, resume
linkage across two service lifetimes on one journal, and the
``traceview`` assembler's connectivity verdict (orphan detection,
resume links, process tracks) over synthetic failover-shaped journals
— plus the ``obsctl trace`` CLI and the ``trace_orphan_spans`` SLO
rule round trip.
"""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.obs import traceview
from raft_tpu.obs import trendstore as T
from raft_tpu.obs.tracing import TRACE_HEADER, TraceContext
from raft_tpu.serve import ServeConfig, SweepService
from raft_tpu.serve import journal as wal


def stub_factory(mode, fowt, ncases, **kw):
    def run(Hs, Tp, beta):
        Hs = np.asarray(Hs)
        return {"std": np.stack([np.full(6, float(h)) for h in Hs]),
                "iters": np.full(len(Hs), 3),
                "converged": np.ones(len(Hs), bool)}
    run.ncases = ncases
    run.cache_state = "stub"
    return run


def _cfg(tmp_path=None, **kw):
    base = dict(queue_max=8, batch_cases=2, window_s=0.02,
                batch_deadline_s=5.0, retry_base_s=0.01,
                degrade_after=99)
    if tmp_path is not None:
        base["journal_dir"] = str(tmp_path)
    base.update(kw)
    return ServeConfig(**base)


def _load_obsctl():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "obsctl.py")
    spec = importlib.util.spec_from_file_location("obsctl", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# unit: the context itself
# ---------------------------------------------------------------------------

def test_trace_context_mint_child_and_header_roundtrip():
    ctx = TraceContext.mint()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert ctx.parent_id is None
    kid = ctx.child()
    assert kid.trace_id == ctx.trace_id
    assert kid.span_id != ctx.span_id
    assert kid.parent_id == ctx.span_id
    back = TraceContext.parse(kid.to_header())
    assert back.trace_id == kid.trace_id
    assert back.span_id == kid.span_id
    # bare "<trace>-<span>" is accepted too
    bare = TraceContext.parse(f"{ctx.trace_id}-{ctx.span_id}")
    assert bare.span_id == ctx.span_id


@pytest.mark.parametrize("bad", [
    "", "garbage", "00-short-short-01", "00" * 40,
    f"00-{'g' * 32}-{'a' * 16}-01",          # non-hex
    f"00-{'0' * 32}-{'a' * 16}-01",          # all-zero trace id
])
def test_trace_context_malformed_headers_rejected(bad):
    assert TraceContext.parse(bad) is None
    # from_header never fails — a broken caller still gets traced
    minted = TraceContext.from_header(bad)
    assert len(minted.trace_id) == 32


def test_trace_context_dict_roundtrip():
    kid = TraceContext.mint().child()
    d = kid.as_dict()
    assert set(d) == {"trace_id", "span_id", "parent_id"}
    assert TraceContext.from_dict(d) == kid
    assert TraceContext.from_dict(None) is None
    assert TraceContext.from_dict({"trace_id": "nope"}) is None
    # an invalid parent is dropped, not fatal
    got = TraceContext.from_dict({**d, "parent_id": "zz"})
    assert got.parent_id is None


# ---------------------------------------------------------------------------
# WAL round trip + phase breakdown through a stub service
# ---------------------------------------------------------------------------

def test_submit_trace_propagates_to_wal_provenance_and_phases(tmp_path):
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path))
    inbound = TraceContext.mint()
    t = svc.submit(2.0, 9.0, 0.0, trace=inbound.to_header())
    t2 = svc.submit(3.0, 8.0, 10.0)            # no header: minted root
    svc.start()
    res = t.result(30.0)
    res2 = t2.result(30.0)
    summary = svc.stop()
    assert res.ok and res2.ok

    prov = (res.extra or {})["provenance"]["trace"]
    # the service span is a CHILD of the inbound header's span
    assert prov["trace_id"] == inbound.trace_id
    assert prov["parent_id"] == inbound.span_id
    assert prov["span_id"] != inbound.span_id
    prov2 = (res2.extra or {})["provenance"]["trace"]
    assert prov2["trace_id"] != inbound.trace_id
    assert "parent_id" not in prov2            # minted root

    state = wal.replay(str(tmp_path))
    assert state["admitted"][t.seq]["trace"] == prov
    assert state["completed"][t.seq]["trace"] == prov
    # replay() folds batch records away — read the raw stream
    batch_recs = [r for _p, r in traceview.scan([str(tmp_path)])
                  if r.get("type") == "batch"]
    assert any(prov in (b.get("traces") or []) for b in batch_recs)

    # phase breakdown: summary percentiles + the labeled histogram
    for key in ("phase_admission_p50_s", "phase_queue_wait_p99_s",
                "phase_solve_p50_s", "phase_delivery_p99_s"):
        assert key in summary and summary[key] >= 0.0
    assert "raft_tpu_serve_request_phase_seconds" in obs.snapshot()
    from raft_tpu.obs import metrics as M
    assert "raft_tpu_serve_request_phase_seconds" in M.exposition()


def test_batch_membership_assembles_with_flow_events(tmp_path):
    svc = SweepService(runner_factory=stub_factory,
                       config=_cfg(tmp_path, window_s=0.2))
    ta = svc.submit(2.0, 9.0, 0.0)
    tb = svc.submit(3.0, 8.0, 10.0)            # same window, same batch
    svc.start()
    assert ta.result(30.0).ok and tb.result(30.0).ok
    svc.stop()
    dirs = [str(tmp_path)]
    tids = traceview.trace_ids(dirs)
    assert len(tids) == 2
    for tid in tids:
        asm = traceview.assemble(tid, dirs)
        assert len(asm["spans"]) == 1
        assert asm["orphan_spans"] == 0 and asm["open_spans"] == 0
        assert asm["batches"], "batch record lost its member context"
        chrome = traceview.chrome_trace(asm)
        phs = [e["ph"] for e in chrome["traceEvents"]]
        assert "X" in phs and "M" in phs
        # batch membership renders as a flow arrow pair + an instant
        assert "s" in phs and "f" in phs and "i" in phs


def test_resume_linkage_across_two_service_lifetimes(tmp_path):
    # lifetime A admits (worker never started) and "dies" — the WAL
    # holds the admit with A's context
    svc_a = SweepService(runner_factory=stub_factory,
                         config=_cfg(tmp_path))
    t_a = svc_a.submit(2.0, 9.0, 0.0)
    ctx_a = wal.replay(str(tmp_path))["admitted"][t_a.seq]["trace"]

    # lifetime B recovers the same journal and finishes the request
    svc_b = SweepService(runner_factory=stub_factory,
                         config=_cfg(tmp_path))
    info = svc_b.recover()
    svc_b.start()
    res = info["tickets"][t_a.seq].result(30.0)
    svc_b.stop()
    assert res.ok
    prov = (res.extra or {})["provenance"]["trace"]
    # same trace, fresh span, parented on the dead lifetime's span
    assert prov["trace_id"] == ctx_a["trace_id"]
    assert prov["span_id"] != ctx_a["span_id"]
    assert prov["parent_id"] == ctx_a["span_id"]

    asm = traceview.assemble(ctx_a["trace_id"], [str(tmp_path)])
    assert len(asm["spans"]) == 2
    assert asm["orphan_spans"] == 0            # B's parent resolves to A
    assert asm["resume_links"] == 1            # ... across lifetimes
    assert asm["process_tracks"] == 2          # two run_ids, one dir
    chrome = traceview.chrome_trace(asm)
    links = [e for e in chrome["traceEvents"]
             if e.get("cat") == "link"]
    assert {"s", "f"} == {e["ph"] for e in links}


# ---------------------------------------------------------------------------
# assembler verdicts over synthetic failover-shaped journals
# ---------------------------------------------------------------------------

def _write_journal(d, recs):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, traceview.JOURNAL_FILENAME), "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _failover_tree(root):
    """A hand-built two-host trace: host A admits + checkpoints, dies;
    host B re-admits as a child span and completes."""
    tid = "ab" * 16
    t0 = 1700000000.0
    a = {"trace_id": tid, "span_id": "aa" * 8}
    b = {"trace_id": tid, "span_id": "bb" * 8, "parent_id": "aa" * 8}
    mirror = [
        {"t": t0, "type": "begin", "run_id": "hostA", "pid": 11},
        {"t": t0 + 1, "type": "admit", "seq": 0, "rdigest": "r0",
         "trace": a},
        {"t": t0 + 2, "type": "batch", "batch_id": 1, "seqs": [0],
         "mode": "full", "traces": [a]},
        {"t": t0 + 3, "type": "ckpt", "seq": 0, "step": 2,
         "cdigest": "c0", "trace": a},
    ]
    succ = [
        {"t": t0 + 10, "type": "begin", "run_id": "hostB", "pid": 22},
        {"t": t0 + 11, "type": "admit", "seq": 0, "rdigest": "r0",
         "trace": b},
        {"t": t0 + 12, "type": "complete", "seq": 0, "rdigest": "r0",
         "digest": "d0", "trace": b},
    ]
    _write_journal(os.path.join(root, "mirror"), mirror)
    _write_journal(os.path.join(root, "successor", "journal"), succ)
    return tid


def test_traceview_failover_connected_and_orphan_detection(tmp_path):
    tid = _failover_tree(str(tmp_path))
    dirs = traceview.discover_journal_dirs(str(tmp_path))
    assert len(dirs) == 2                      # mirror + successor
    assert traceview.trace_ids(dirs) == [tid]
    asm = traceview.assemble(tid, dirs)
    assert len(asm["spans"]) == 2
    assert asm["process_tracks"] == 2
    assert asm["orphan_spans"] == 0
    assert asm["resume_links"] == 1
    assert asm["open_spans"] == 1              # host A died mid-flight
    assert [i["name"] for i in asm["instants"]] == ["ckpt step=2"]

    # corrupt host B's inherited parent: the later span's parent no
    # longer resolves anywhere -> an orphan (the earliest span alone
    # is entitled to an out-of-WAL parent)
    broken = os.path.join(str(tmp_path), "broken")
    _failover_tree(broken)
    succ = os.path.join(broken, "successor", "journal",
                        traceview.JOURNAL_FILENAME)
    text = open(succ).read().replace("bbbbbbbbbbbbbbbb", "cc" * 8)
    open(succ, "w").write(text.replace("aaaaaaaaaaaaaaaa", "ff" * 8))
    part = traceview.assemble(
        tid, traceview.discover_journal_dirs(broken))
    assert part["orphan_spans"] == 1 == len(part["spans"]) - 1
    assert part["resume_links"] == 0


def test_obsctl_trace_cli_and_slo_rule(tmp_path):
    obsctl = _load_obsctl()
    tid = _failover_tree(str(tmp_path / "soak"))
    out = str(tmp_path / "trace.json")
    db = str(tmp_path / "trend.sqlite")
    rc = obsctl.main(["trace", tid, "--journal-dir",
                      str(tmp_path / "soak"), "--expect-resume",
                      "--out", out, "--trend-db", db])
    assert rc == 0
    chrome = json.load(open(out))
    assert chrome["otherData"]["orphan_spans"] == 0
    assert chrome["otherData"]["process_tracks"] == 2
    assert any(e["ph"] == "X" for e in chrome["traceEvents"])
    # --all over the same tree: one trace, still connected
    assert obsctl.main(["trace", "--all", "--journal-dir",
                        str(tmp_path / "soak")]) == 0
    # a broken tree (the successor's inherited parent corrupted) must
    # exit 1
    broken = str(tmp_path / "broken")
    _failover_tree(broken)
    succ = os.path.join(broken, "successor", "journal",
                        traceview.JOURNAL_FILENAME)
    text = open(succ).read().replace("aaaaaaaaaaaaaaaa", "ff" * 8)
    open(succ, "w").write(text)
    assert obsctl.main(["trace", tid, "--journal-dir", broken]) == 1

    # the appended trend row feeds the zero-tolerance SLO rule
    rows = T.TrendStore(db).rows()
    assert rows and rows[0]["facts"]["trace_orphan_spans"] == 0
    report = T.evaluate_slo(rows, None)
    by_name = {r["name"]: r for r in report["results"]}
    assert by_name["trace_orphan_spans"]["ok"]
    assert not by_name["trace_orphan_spans"].get("skipped")
    # ... and violates when an orphan lands in the store
    # (status stays "ok": the row records the measurement, the rule
    # does the gating — evaluate_slo only reads status-ok rows)
    T.TrendStore(db).append({
        "run_id": "trace-broken", "kind": "trace", "status": "ok",
        "started_at": "2026-01-01T00:00:00Z",
        "extra": {"trace": {"trace_orphan_spans": 1}}})
    assert obsctl.main(["slo", "--db", db]) == 1


def test_trendstore_phase_and_trace_fact_folding():
    doc = {"run_id": "x", "kind": "serve", "status": "ok",
           "extra": {"serve": {"completed": 2,
                               "phase_solve_p50_s": 0.125,
                               "phase_queue_wait_p99_s": 0.5},
                     "trace": {"trace_orphan_spans": 0,
                               "trace_resume_links": 1}}}
    facts = T.facts_from_manifest(doc)
    assert facts["serve_phase_solve_p50_s"] == 0.125
    assert facts["serve_phase_queue_wait_p99_s"] == 0.5
    assert facts["trace_orphan_spans"] == 0
    assert facts["trace_resume_links"] == 1
