"""The statistical trend-regression sentinel (``obsctl regress``) and
the ``obsctl trend --import`` snapshot backfill.

``evaluate_regression`` compares each (kind, fingerprint) group's
newest trend row against its own rolling median/MAD history — no
hand-set thresholds; the CLI layer backfills committed BENCH/MULTICHIP
snapshots into a store and exits 1 on unwaived drift.
"""
import json
import os
import subprocess
import sys

import pytest

from raft_tpu.obs import trendstore as T

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBSCTL = os.path.join(REPO, "tools", "obsctl.py")


def row(i, value, *, kind="bench-round", status="ok",
        metric="solves/sec", fact="result_value", **extra):
    facts = {"bench_metric": metric, fact: value}
    facts.update(extra)
    return {"run_id": f"r{i:03d}", "kind": kind, "status": status,
            "started_at": f"2026-03-{i:02d}T00:00:00", "facts": facts}


def history(values, **kw):
    """Newest-first rows (as TrendStore.rows returns them)."""
    n = len(values)
    return [row(n - i, v, **kw) for i, v in enumerate(values)]


# ---------------------------------------------------------------------------
# the math
# ---------------------------------------------------------------------------

def test_noise_passes():
    rep = T.evaluate_regression(
        history([1001.0, 999.0, 1000.5, 998.5, 1000.0]))
    assert rep["ok"] and rep["checked"] == 1 and not rep["regressions"]


def test_two_sided_detection():
    for cand in (480.0, 2100.0):          # slowdown AND suspicious jump
        rep = T.evaluate_regression(
            history([cand, 999.0, 1000.5, 998.5, 1000.0]))
        assert not rep["ok"]
        (f,) = rep["regressions"]
        assert f["fact"] == "result_value" and f["value"] == cand
        assert f["n"] == 4 and not f["waived"]


def test_min_history_guard():
    rep = T.evaluate_regression(history([480.0, 999.0, 1000.5]))
    assert rep["ok"] and rep["checked"] == 0
    assert rep["groups"][0]["skipped"] == "insufficient history"


def test_rel_floor_absorbs_dead_flat_baselines():
    # MAD 0 on a flat history: a 2% wiggle stays inside the 5% floor,
    # a 20% break does not
    assert T.evaluate_regression(
        history([102.0, 100.0, 100.0, 100.0, 100.0]))["ok"]
    assert not T.evaluate_regression(
        history([120.0, 100.0, 100.0, 100.0, 100.0]))["ok"]


def test_fingerprint_isolates_baselines():
    rows = history([999.0, 1000.5, 998.5, 1000.0])
    rows.insert(0, row(9, 480.0, metric="other metric"))
    rep = T.evaluate_regression(rows)
    assert rep["ok"]                      # new metric = new baseline
    assert any(g.get("skipped") for g in rep["groups"])


def test_non_ok_rows_never_qualify():
    rows = history([480.0, 999.0, 1000.5, 998.5, 1000.0])
    rows[0]["status"] = "failed"          # the bad candidate is non-ok
    rep = T.evaluate_regression(rows)
    assert rep["ok"]


def test_bookkeeping_and_fingerprint_facts_not_drift_checked():
    rows = history([1000.0, 1000.0, 1000.0, 1000.0, 1000.0],
                   exec_cache_warm=0.0)
    rows[0]["facts"]["exec_cache_warm"] = 1.0   # warmth flip: expected
    rep = T.evaluate_regression(rows)
    assert rep["ok"] and rep["checked"] == 1    # only result_value


def test_waivers():
    rows = history([480.0, 999.0, 1000.5, 998.5, 1000.0])
    for waiver in ("result_value", "bench-round:result_value",
                   {"fact": "result_value"},
                   {"kind": "bench-round", "fact": "result_value"}):
        rep = T.evaluate_regression(rows, waivers=[waiver])
        assert rep["ok"], waiver
        assert rep["regressions"][0]["waived"]
    rep = T.evaluate_regression(rows, waivers=["other:result_value"])
    assert not rep["ok"]


# ---------------------------------------------------------------------------
# the CLI: trend --import + regress exit codes
# ---------------------------------------------------------------------------

def _run(*args, cwd=REPO):
    return subprocess.run([sys.executable, OBSCTL, *args], cwd=cwd,
                          capture_output=True, text=True,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})


@pytest.fixture(scope="module")
def backfilled_db(tmp_path_factory):
    db = str(tmp_path_factory.mktemp("regress") / "trend.sqlite")
    snaps = (sorted(f for f in os.listdir(REPO)
                    if f.startswith("BENCH_r") and f.endswith(".json"))
             + sorted(f for f in os.listdir(REPO)
                      if f.startswith("MULTICHIP_r")
                      and f.endswith(".json")))
    assert snaps, "committed bench snapshots missing"
    p = _run("trend", "--import", "--db", db, *snaps)
    assert p.returncode == 0, p.stderr
    return db


def test_import_backfills_snapshots(backfilled_db):
    rows = T.TrendStore(backfilled_db).rows()
    kinds = {r["kind"] for r in rows}
    assert kinds == {"bench-round", "multichip"}
    ok_bench = [r for r in rows if r["kind"] == "bench-round"
                and r["status"] == "ok"]
    assert ok_bench and all("bench_metric" in r["facts"]
                            and "result_value" in r["facts"]
                            for r in ok_bench)
    # failed rounds import as NON-ok so they never become baselines
    assert any(r["status"] != "ok" for r in rows)


def test_regress_exit_0_on_backfilled_history(backfilled_db):
    p = _run("regress", "--db", backfilled_db)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "obsctl regress: OK" in p.stdout


def test_regress_exit_1_on_synthetic_regression(tmp_path):
    db = str(tmp_path / "trend.sqlite")
    T.TrendStore(db).append_rows(
        history([480.0, 999.0, 1000.5, 998.5, 1000.0]))
    p = _run("regress", "--db", db, "--json")
    assert p.returncode == 1
    rep = json.loads(p.stdout)
    assert not rep["ok"]
    assert rep["regressions"][0]["fact"] == "result_value"
    # a waiver file flips it back to 0
    wf = tmp_path / "waivers.json"
    wf.write_text(json.dumps({"waivers": ["bench-round:result_value"]}))
    p = _run("regress", "--db", db, "--waivers", str(wf))
    assert p.returncode == 0, p.stdout + p.stderr


def test_regress_bad_inputs_exit_2(tmp_path):
    p = _run("regress", "--db", str(tmp_path / "missing.sqlite"))
    assert p.returncode == 2
    db = str(tmp_path / "t.sqlite")
    T.TrendStore(db).append_rows(history([1.0]))
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    p = _run("regress", "--db", db, "--waivers", str(bad))
    assert p.returncode == 2


def test_import_requires_db_and_inputs(tmp_path):
    p = _run("trend", "--import")
    assert p.returncode == 2
    p = _run("trend", "--import", "--db", str(tmp_path / "t.sqlite"))
    assert p.returncode == 2
