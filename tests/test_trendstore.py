"""Trend store (obs/trendstore.py) + the obsctl tail/serve/slo surface.

Pure-stdlib tests: facts extraction from manifests, the SQLite store
round trip (append/upsert/ingest), SLO rule evaluation (percentiles,
ratios, windows, skip-vs-required), the committed golden-run fixture
gate CI runs, the Prometheus page parser, and the `obsctl` subcommands
— `slo` exit codes, `trend --db`, `tail`, and an in-process `serve`
scrape of /healthz /metrics /runs /events.  No model solves, no jax.
"""
import json
import os
import sys
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from raft_tpu.obs import events, trendstore  # noqa: E402
from tools import obsctl  # noqa: E402

GOLDEN_FIXTURE = os.path.join(REPO, "tests", "golden",
                              "trend_fixture.jsonl")


def _manifest_doc(run_id="r1", status="ok", n_cases=3, duration=90.0,
                  **extra):
    return {
        "schema": "raft_tpu.run_manifest/v1", "run_id": run_id,
        "kind": "analyzeCases", "status": status,
        "started_at": "2026-08-02T10:00:00+00:00",
        "finished_at": "2026-08-02T10:01:30+00:00",
        "duration_s": duration,
        "environment": {"git_sha": "abc", "hostname": "h", "pid": 42},
        "config": {"nCases": n_cases}, "phases": [],
        "metrics": {"raft_tpu_probe_events_total": {
            "kind": "counter", "series": [
                {"labels": {"probe": "statics_newton"}, "value": 3.0},
                {"labels": {"probe": "drag_fixed_point"}, "value": 17.0},
            ]}},
        "probe_attempts": [],
        "extra": {
            "failed_cases": [{"case": 1}],
            "resumed_cases": [0],
            "recovery": {"attempts": [
                {"outcome": "failed"}, {"outcome": "recovered"}]},
            "host_transfers": {
                "total": {"events": 15, "arrays": 40, "bytes": 1000},
                "per_case": {"statics": 1.0, "dynamics": 4.0}},
            "exec_cache": {"state": "hit"},
            **extra,
        },
    }


# ---------------------------------------------------------------------------
# facts extraction + store round trip
# ---------------------------------------------------------------------------

def test_facts_from_manifest():
    facts = trendstore.facts_from_manifest(_manifest_doc())
    assert facts["cases_total"] == 3
    assert facts["s_per_case"] == pytest.approx(30.0)
    assert facts["cases_failed"] == 1 and facts["cases_resumed"] == 1
    assert facts["recovery_attempts"] == 2
    assert facts["recovery_recovered"] == 1
    assert facts["transfer_events"] == 15
    assert facts["transfers_per_case_statics"] == 1.0
    assert facts["transfers_per_case_dynamics"] == 4.0
    assert facts["exec_cache_warm"] == 1
    assert facts["probe_events"] == 20.0
    # missing structure -> missing facts, never an error
    assert trendstore.facts_from_manifest({}) == {}


def _serve_manifest(run_id="srv_a", rejected=4, retries=6,
                    recovered=3, misses=5, unhandled=0):
    return {
        "schema": "raft_tpu.run_manifest/v1", "run_id": run_id,
        "kind": "serve", "status": "ok",
        "started_at": "2026-08-04T00:00:00+00:00", "duration_s": 30.0,
        "environment": {"hostname": "h", "pid": 42},
        "config": {}, "metrics": {},
        "extra": {"serve": {
            "requests": 16, "admitted": 12, "rejected": rejected,
            "completed": 10, "failed": 2, "quarantined": 1,
            "retries": retries, "retried_recovered": recovered,
            "deadline_misses": misses, "unhandled": unhandled,
            "batches": 7, "abandoned_batches": 2,
            "n_mode_transitions": 0, "mode": "full",
            "p50_latency_s": 0.8, "p99_latency_s": 2.5}},
    }


def test_facts_from_serve_manifest():
    facts = trendstore.facts_from_manifest(_serve_manifest())
    assert facts["serve_requests"] == 16
    assert facts["serve_rejected"] == 4
    assert facts["serve_retries"] == 6
    assert facts["serve_retried_recovered"] == 3
    assert facts["serve_deadline_misses"] == 5
    assert facts["serve_unhandled"] == 0
    assert facts["serve_p99_latency_s"] == pytest.approx(2.5)
    assert facts["serve_mode"] == "full"


def test_serve_slo_rules_gate_soak_rows(tmp_path):
    """The ISSUE's three serve gates (admission-reject ratio, retry-
    success ratio, deadline-miss count) plus the unhandled-error gate
    evaluate over serve trend rows and flag each failure mode."""
    db = str(tmp_path / "t.sqlite")
    store = trendstore.TrendStore(db)
    store.append(_serve_manifest("srv_ok"))
    report = trendstore.evaluate_slo(store.rows())
    by = {r["name"]: r for r in report["results"]}
    assert report["ok"]
    assert by["serve_admission_reject_ratio"]["value"] == \
        pytest.approx(4 / 16)
    assert by["serve_retry_success_ratio"]["value"] == \
        pytest.approx(0.5)
    assert by["serve_deadline_miss_count"]["value"] == 5.0
    assert not by["serve_unhandled_errors"]["skipped"]
    # each gate flags its own failure mode
    store.append(_serve_manifest("srv_shed", rejected=100))
    store.append(_serve_manifest("srv_bug", unhandled=3))
    store.append(_serve_manifest("srv_hang", misses=99))
    store.append(_serve_manifest("srv_spin", retries=10, recovered=1))
    report = trendstore.evaluate_slo(store.rows())
    by = {r["name"]: r for r in report["results"]}
    assert not report["ok"]
    assert not by["serve_admission_reject_ratio"]["ok"]
    assert not by["serve_retry_success_ratio"]["ok"]
    assert not by["serve_deadline_miss_count"]["ok"]
    assert not by["serve_unhandled_errors"]["ok"]
    # analyzeCases-only stores skip the serve rules (fresh checkouts)
    empty = trendstore.TrendStore(str(tmp_path / "e.sqlite"))
    report = trendstore.evaluate_slo(empty.rows())
    assert report["ok"]
    assert all(r["skipped"] for r in report["results"]
               if r["name"].startswith("serve_"))


def test_store_append_upsert_and_rows(tmp_path):
    db = str(tmp_path / "trend.sqlite")
    store = trendstore.TrendStore(db)
    store.append(_manifest_doc("run_a", duration=60.0))
    store.append(_manifest_doc("run_b", duration=90.0))
    store.append(_manifest_doc("run_a", duration=61.0))   # upsert
    assert store.count() == 2
    rows = store.rows(kind="analyzeCases", status="ok")
    assert {r["run_id"] for r in rows} == {"run_a", "run_b"}
    a = next(r for r in rows if r["run_id"] == "run_a")
    assert a["duration_s"] == 61.0
    assert a["facts"]["s_per_case"] == pytest.approx(61.0 / 3)
    assert a["hostname"] == "h" and a["pid"] == 42
    assert store.rows(kind="bench") == []
    assert store.rows(limit=1)[0]["run_id"] in ("run_a", "run_b")


def test_store_ingest_manifest_and_jsonl(tmp_path):
    db = str(tmp_path / "t.sqlite")
    mani = tmp_path / "x.manifest.json"
    mani.write_text(json.dumps(_manifest_doc("ing_a")))
    store = trendstore.TrendStore(db)
    n = store.ingest([str(mani), GOLDEN_FIXTURE])
    assert n == 1 + 6
    assert store.count() == 7
    assert trendstore.load_rows(str(tmp_path / "missing.json")) == []


# ---------------------------------------------------------------------------
# SLO evaluation
# ---------------------------------------------------------------------------

def _rows(values, kind="analyzeCases", status="ok", fact="s_per_case"):
    return [{"run_id": f"r{i}", "kind": kind, "status": status,
             "facts": {fact: v}} for i, v in enumerate(values)]


def test_slo_percentile_window_and_ops():
    rows = _rows([10.0, 20.0, 30.0, 40.0, 1000.0])
    rule = {"name": "p50", "kind": "analyzeCases", "fact": "s_per_case",
            "agg": "p50", "op": "<=", "threshold": 25.0}
    rep = trendstore.evaluate_slo(rows, [rule])
    assert not rep["ok"]                       # p50 over all 5 = 30
    rep = trendstore.evaluate_slo(rows, [{**rule, "window": 4}])
    assert rep["ok"]                           # newest 4 -> p50 = 20
    rep = trendstore.evaluate_slo(rows, [
        {"name": "mx", "fact": "s_per_case", "agg": "max", "op": "<",
         "threshold": 1000.0}])
    assert not rep["ok"]
    # failed-status rows never enter an ok-status rule
    rep = trendstore.evaluate_slo(
        _rows([5.0]) + _rows([9999.0], status="failed"),
        [{**rule, "window": 10}])
    assert rep["ok"] and rep["results"][0]["n"] == 1


def test_slo_ratio_skip_and_required():
    rows = [{"run_id": "a", "kind": "analyzeCases", "status": "ok",
             "facts": {"cases_failed": 1, "cases_total": 4}},
            {"run_id": "b", "kind": "analyzeCases", "status": "ok",
             "facts": {"cases_failed": 0, "cases_total": 4}}]
    ratio = {"name": "fr", "kind": "analyzeCases", "fact": "cases_failed",
             "denom": "cases_total", "agg": "ratio", "op": "<=",
             "threshold": 0.2}
    rep = trendstore.evaluate_slo(rows, [ratio])
    assert rep["ok"]
    assert rep["results"][0]["value"] == pytest.approx(0.125)
    # no qualifying data: skipped-ok by default, a violation if required
    rep = trendstore.evaluate_slo([], [ratio])
    assert rep["ok"] and rep["results"][0]["skipped"]
    rep = trendstore.evaluate_slo([], [{**ratio, "required": True}])
    assert not rep["ok"]


def test_golden_fixture_passes_default_rules():
    """The committed golden-run trend fixture must clear the built-in
    SLO gate — this is the same check CI's `obsctl slo` step runs."""
    rows = trendstore.load_rows(GOLDEN_FIXTURE)
    assert len(rows) == 6
    rep = trendstore.evaluate_slo(rows)
    assert rep["ok"], rep
    # the deliberately-running row is excluded from every ok-gated rule
    assert all(r["n"] <= 4 for r in rep["results"])


def test_parse_prometheus_and_metric_rules():
    text = (
        "# raft_tpu exposition pid=1 hostname=h\n"
        "# HELP raft_tpu_build_info x\n"
        "# TYPE raft_tpu_build_info gauge\n"
        'raft_tpu_build_info{git_sha="abc",pid="1"} 1\n'
        'raft_tpu_live_cases_done 2\n'
        'raft_tpu_trend_runs{kind="analyzeCases",status="ok"} 4\n'
        'raft_tpu_trend_runs{kind="analyzeCases",status="failed"} 1\n')
    series = trendstore.parse_prometheus(text)
    assert series["raft_tpu_build_info"][0][0]["git_sha"] == "abc"
    assert len(series["raft_tpu_trend_runs"]) == 2
    rep = trendstore.evaluate_metric_rules(series, [
        {"name": "alive", "metric": "raft_tpu_build_info", "op": ">=",
         "threshold": 1, "required": True},
        {"name": "ok_runs", "metric": "raft_tpu_trend_runs",
         "labels": {"status": "ok"}, "op": ">=", "threshold": 2},
    ])
    assert rep["ok"]
    rep = trendstore.evaluate_metric_rules(series, [
        {"name": "failed", "metric": "raft_tpu_trend_runs",
         "labels": {"status": "failed"}, "op": "<=", "threshold": 0}])
    assert not rep["ok"]


# ---------------------------------------------------------------------------
# obsctl: slo / trend --db / serve
# ---------------------------------------------------------------------------

def test_obsctl_slo_fixture_gate_and_violation(tmp_path, capsys):
    rc = obsctl.main(["slo", "--fixture", GOLDEN_FIXTURE])
    out = capsys.readouterr().out
    assert rc == 0 and "obsctl slo: OK" in out
    # a tightened rule file flips the exit code
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps([
        {"name": "impossible", "kind": "analyzeCases",
         "fact": "s_per_case", "agg": "p50", "op": "<=",
         "threshold": 0.001}]))
    rc = obsctl.main(["slo", "--fixture", GOLDEN_FIXTURE,
                      "--rules", str(rules)])
    out = capsys.readouterr().out
    assert rc == 1 and "VIOLATION" in out
    with pytest.raises(SystemExit) as exc:
        obsctl.main(["slo"])                 # no store anywhere
    assert exc.value.code == 2


def test_obsctl_trend_db_renders_and_counts_running(tmp_path, capsys):
    db = str(tmp_path / "trend.sqlite")
    store = trendstore.TrendStore(db)
    store.ingest([GOLDEN_FIXTURE])
    rc = obsctl.main(["trend", "--db", db])
    out = capsys.readouterr().out
    assert rc == 0
    assert "trend/analyzeCases" in out
    # the killed-run stub row is counted, not treated as a baseline
    assert "1 run(s) still marked running" in out
    rc = obsctl.main(["trend", "--db", db, "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert rc == 0 and len(rows) == 6


def test_obsctl_serve_endpoints(tmp_path):
    import threading

    # a store + an in-flight event file for the live half of /metrics
    db = str(tmp_path / "trend.sqlite")
    trendstore.TrendStore(db).ingest([GOLDEN_FIXTURE])
    rec = events.FlightRecorder(
        str(tmp_path / "analyzeCases_live01.events.jsonl"),
        run_id="live01", kind="analyzeCases")
    rec.emit("case_start", case=0, n_cases=3)
    rec.emit("case_end", case=0, n_cases=3, ok=True, s=2.0)
    # recorder left open: the run is "in flight" from the scraper's view

    srv = obsctl.make_server(0, db=db, obs_dir=str(tmp_path))
    host, port = srv.server_address[:2]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        base = f"http://{host}:{port}"
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read().decode())
        assert health["ok"] is True and health["trend_runs"] == 6
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            page = r.read().decode()
        assert page.startswith("# raft_tpu exposition pid=")
        assert "raft_tpu_build_info{" in page
        series = trendstore.parse_prometheus(page)
        trend_ok = [v for labels, v in series["raft_tpu_trend_runs"]
                    if labels == {"kind": "analyzeCases", "status": "ok"}]
        assert trend_ok == [4.0]
        assert series["raft_tpu_live_cases_done"][0][1] == 1.0
        assert series["raft_tpu_live_cases_total"][0][1] == 3.0
        live = series["raft_tpu_live_run"][0][0]
        assert live["run_id"] == "live01" and live["status"] == "running"
        with urllib.request.urlopen(base + "/runs?limit=3",
                                    timeout=10) as r:
            runs = json.loads(r.read().decode())
        assert len(runs) == 3 and all("facts" in row for row in runs)
        with urllib.request.urlopen(base + "/events?n=10",
                                    timeout=10) as r:
            lines = r.read().decode().strip().splitlines()
        assert json.loads(lines[-1])["type"] == "case_end"
        with urllib.request.urlopen(base + "/nope", timeout=10) as r:
            pass
    except urllib.error.HTTPError as e:
        assert e.code == 404                    # the /nope probe above
    finally:
        rec.close()
        srv.shutdown()
        srv.server_close()


def test_obsctl_serve_smoke_flag(capsys):
    rc = obsctl.main(["serve", "--port", "0", "--smoke"])
    out = capsys.readouterr().out
    assert rc == 0 and "obsctl serve --smoke: OK" in out


def test_obsctl_slo_url_gates_live_metrics(tmp_path):
    """The acceptance wiring: `obsctl serve` exposes live /metrics that
    `obsctl slo --url` can gate on."""
    import threading

    db = str(tmp_path / "trend.sqlite")
    trendstore.TrendStore(db).ingest([GOLDEN_FIXTURE])
    srv = obsctl.make_server(0, db=db, obs_dir=str(tmp_path))
    host, port = srv.server_address[:2]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    rules = tmp_path / "live_rules.json"
    rules.write_text(json.dumps([
        {"name": "build_info_present", "metric": "raft_tpu_build_info",
         "op": ">=", "threshold": 1, "required": True},
        {"name": "ok_runs", "metric": "raft_tpu_trend_runs",
         "labels": {"kind": "analyzeCases", "status": "ok"},
         "op": ">=", "threshold": 4, "required": True},
    ]))
    try:
        rc = obsctl.main(["slo", "--url", f"http://{host}:{port}/metrics",
                          "--rules", str(rules)])
        assert rc == 0
    finally:
        srv.shutdown()
        srv.server_close()


def test_finish_run_appends_trend_store(tmp_path):
    from raft_tpu import obs

    obs.configure(str(tmp_path))
    m = obs.RunManifest.begin(kind="unitrun", config={"nCases": 2},
                              devices=False)
    paths = obs.finish_run(m, status="ok")
    assert paths["trend"] == str(tmp_path / "trend.sqlite")
    (row,) = trendstore.TrendStore(paths["trend"]).rows()
    assert row["run_id"] == m.run_id and row["kind"] == "unitrun"
    # RAFT_TPU_TREND=0 disables the append
    os.environ["RAFT_TPU_TREND"] = "0"
    try:
        m2 = obs.RunManifest.begin(kind="unitrun", devices=False)
        paths2 = obs.finish_run(m2, status="ok")
        assert paths2["trend"] is None
        assert trendstore.TrendStore(paths["trend"]).count() == 1
    finally:
        os.environ.pop("RAFT_TPU_TREND", None)
    obs.reset_all()
