"""Design-variant sweep axis (reference: raft/parametersweep.py:39-100 —
the serial 3^5 VolturnUS-S geometry study; SURVEY §7 step 6).

Validates that the traced geometry rebuild reproduces a host-side design
rebuild, that the in-jit Newton statics converges, and that sharding the
variant axis over an 8-device Mesh gives the same answers as a plain vmap.
"""
import copy
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import yaml
from jax.sharding import Mesh

from raft_tpu.models.fowt import build_fowt, fowt_pose, fowt_statics
from raft_tpu.parallel import variants as vr

W = np.arange(0.01, 0.20 + 0.005, 0.01) * 2 * np.pi   # 20 bins for speed


@pytest.fixture(scope="module")
def volturn_design(reference_test_data):
    with open(os.path.join(reference_test_data, "VolturnUS-S.yaml")) as f:
        return yaml.safe_load(f)


@pytest.fixture(scope="module")
def base(volturn_design):
    return build_fowt(volturn_design, W, depth=600.0)


def _identity_theta(base):
    nmem = len(base.members)
    return dict(
        rA0=np.stack([np.asarray(m.rA0) for m in base.members]),
        rB0=np.stack([np.asarray(m.rB0) for m in base.members]),
        d_scale=np.ones((nmem, 2)),
    )


def test_identity_variant_matches_base(base):
    out = jax.jit(vr.make_variant_solver(base, ballast=False,
                                         newton_iters=10))(
        _identity_theta(base))
    stat = fowt_statics(base, fowt_pose(base, np.zeros(6)))
    np.testing.assert_allclose(out["mass"], stat["M_struc"][0, 0], rtol=1e-12)
    np.testing.assert_allclose(out["displacement"], stat["V"] * 1025,
                               rtol=1e-12)
    np.testing.assert_allclose(out["GMT"], stat["rM"][2] - stat["rCG"][2],
                               rtol=1e-9)
    # unloaded equilibrium: heave from the known VolturnUS-S imbalance
    assert abs(float(out["Xeq"][2]) - (-0.43)) < 0.02


def test_perturbed_variant_matches_host_rebuild(base, volturn_design):
    """One parametersweep-style mutation solved through the traced variant
    axis vs the same design rebuilt from dicts (independent path)."""
    thetas, meta = vr.volturn_grid(volturn_design, factors=(0.85, 1.0, 1.15))
    iv = 0   # all-low corner
    a, b, c, d, e = meta["grid"][iv]

    dd = copy.deepcopy(volturn_design)
    plat = dd["platform"]["members"]
    ccD0 = plat[0]["d"]
    plat[0]["d"] = float(a)
    plat[2]["rA"][0] = plat[2]["rA"][0] * (a / ccD0)
    plat[3]["rA"][0] = plat[3]["rA"][0] * (a / ccD0)
    plat[1]["d"] = float(b)
    plat[0]["rA"][2] = float(c)
    plat[1]["rA"][2] = float(c)
    plat[1]["rA"][0] = float(d)
    plat[1]["rB"][0] = float(d)
    plat[2]["rB"][0] = d - b / 2
    plat[3]["rB"][0] = d - b / 2
    plat[2]["d"][1] = float(e)
    plat[2]["rA"][2] = c + e / 2
    plat[2]["rB"][2] = c + e / 2
    truth = build_fowt(dd, W, depth=600.0)
    stat = fowt_statics(truth, fowt_pose(truth, np.zeros(6)))

    th = {k: v[iv] for k, v in thetas.items()}
    out = jax.jit(vr.make_variant_solver(base, ballast=False,
                                         newton_iters=10))(th)
    # strip-node counts stay at the base discretization, so the rebuilt
    # design (re-discretized) differs at the strip-quantization level
    np.testing.assert_allclose(out["mass"], stat["M_struc"][0, 0], rtol=1e-3)
    np.testing.assert_allclose(out["displacement"], stat["V"] * 1025,
                               rtol=1e-3)
    np.testing.assert_allclose(out["GMT"], stat["rM"][2] - stat["rCG"][2],
                               rtol=5e-3, atol=0.02)


def test_sharded_sweep_matches_vmap(base, volturn_design):
    """Mesh-sharded variant sweep == single-device vmap (and 243 % 8 != 0
    exercises the pad/slice path)."""
    thetas, meta = vr.volturn_grid(volturn_design, factors=(0.9, 1.1))
    nv = len(meta["grid"])
    assert nv == 32

    devices = jax.devices()
    assert len(devices) == 8, "conftest must provide 8 virtual CPU devices"
    mesh = Mesh(np.array(devices), ("designs",))

    out_mesh = vr.sweep_variants(base, thetas, mesh=mesh, ballast=True,
                                 newton_iters=10)
    out_vmap = vr.sweep_variants(base, thetas, mesh=None, ballast=True,
                                 newton_iters=10)
    for key in ("mass", "displacement", "GMT", "offset", "pitch_deg", "std"):
        np.testing.assert_allclose(np.asarray(out_mesh[key]),
                                   np.asarray(out_vmap[key]),
                                   rtol=1e-10, atol=1e-12)
    assert np.isfinite(np.asarray(out_mesh["std"])).all()
    # ballast trim drove every variant's unloaded heave toward zero
    assert np.abs(np.asarray(out_mesh["Xeq"])[:, 2]).max() < 0.05


def test_grid_reproduces_reference_shape(volturn_design):
    thetas, meta = vr.volturn_grid(volturn_design)
    assert meta["shape"] == (3, 3, 3, 3, 3)
    assert len(meta["grid"]) == 243
    assert thetas["rA0"].shape[0] == 243


def test_batched_solver_matches_vmap(base, volturn_design):
    """solver.batched (manually batched fixed point, the TPU fast path —
    vmap around a loop primitive compiles ~300x slower on XLA:TPU) must
    reproduce vmap(solver) exactly: same per-variant convergence
    decisions, same responses."""
    import jax

    from raft_tpu.parallel.variants import make_variant_solver, volturn_grid

    thetas0, _ = volturn_grid(volturn_design, factors=(0.9, 1.1))
    rng = np.random.default_rng(1)
    idx = rng.integers(0, len(thetas0["rA0"]), 6)
    thetas = {k: jnp.asarray(np.asarray(v)[idx]) for k, v in thetas0.items()}
    solver = make_variant_solver(base, Hs=6.0, Tp=12.0, ballast=True,
                                 nIter=5, tol=0.01, newton_iters=8)
    out_v = jax.vmap(solver)(thetas)
    out_b = solver.batched(thetas)
    for key in ("mass", "offset", "pitch_deg", "std", "Xeq", "Xi"):
        np.testing.assert_allclose(np.asarray(out_b[key]),
                                   np.asarray(out_v[key]),
                                   rtol=1e-9, atol=1e-12, err_msg=key)
