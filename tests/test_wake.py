"""Wake coupling (FLORIS-equivalent): Gaussian deficit, farm equilibrium,
power/thrust curves, AEP (reference: raft_model.py:1674-2022)."""
import dataclasses
import types

import numpy as np
import pytest
import yaml

from raft_tpu.models.wake import (calc_aep, find_wake_equilibrium,
                                  gaussian_deficit, power_thrust_curve,
                                  wake_velocities)


def test_gaussian_deficit_shape():
    # no deficit upstream; decays downstream and crosswind; grows with Ct
    assert gaussian_deficit(-2.0, 0.0, 0.8) == 0.0
    d4 = gaussian_deficit(4.0, 0.0, 0.8)
    d8 = gaussian_deficit(8.0, 0.0, 0.8)
    assert 0 < d8 < d4 < 1
    assert gaussian_deficit(4.0, 2.0, 0.8) < d4
    assert gaussian_deficit(4.0, 0.0, 0.4) < d4


def test_wake_velocities_alignment():
    xy = np.array([[0.0, 0.0], [1000.0, 0.0]])
    D, Ct = 200.0, np.array([0.8, 0.8])
    U = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=0.0)
    assert U[0] == pytest.approx(10.0, abs=1e-6)   # upstream untouched
    assert U[1] < 9.0                               # waked
    # crosswind: both free stream
    U90 = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=90.0)
    assert np.allclose(U90, 10.0, atol=1e-3)
    # reversed wind: roles swap
    U180 = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=180.0)
    assert U180[1] == pytest.approx(10.0, abs=1e-6)
    assert U180[0] < 9.0


@pytest.fixture(scope="module")
def pseudo_farm():
    """Two copies of the OC3 FOWT spaced 8D downwind — avoids the heavy
    farm-yaml build; wake functions only need positions + rotors."""
    from raft_tpu.models.fowt import build_fowt

    design = yaml.safe_load(open("/root/reference/designs/OC3spar.yaml"))
    w = np.arange(0.01, 0.2, 0.01) * 2 * np.pi
    f0 = build_fowt(design, w, depth=200.0)
    D = 2 * f0.rotors[0].R_rot
    f1 = dataclasses.replace(f0, x_ref=8.0 * D)
    return types.SimpleNamespace(nFOWT=2, fowtList=[f0, f1])


def test_power_thrust_curve(pseudo_farm):
    curve = power_thrust_curve(pseudo_farm, speeds=np.arange(4.0, 25.0, 2.0))
    assert np.all(curve["Cp"] > 0) and np.all(curve["Cp"] < 0.6)
    assert np.all(curve["Ct"] > 0)
    # NREL 5MW-class turbine: rated power within a factor ~1.3 of 5 MW
    assert 3.5e6 < curve["power"].max() < 7.0e6
    # below rated, Ct high; far above rated (pitched), Ct drops
    assert curve["Ct"][0] > curve["Ct"][-1]


def test_find_wake_equilibrium(pseudo_farm):
    eq = find_wake_equilibrium(pseudo_farm,
                               dict(wind_speed=8.0, wind_heading=0.0))
    assert eq["U"][0] == pytest.approx(8.0, abs=1e-4)
    assert eq["U"][1] < 7.5                       # waked below free stream
    assert eq["power"][1] < eq["power"][0]
    assert eq["iterations"] < 50
    assert eq["case"]["wind_speed"][1] == pytest.approx(eq["U"][1])
    # crosswind: no wake interaction
    eq90 = find_wake_equilibrium(pseudo_farm,
                                 dict(wind_speed=8.0, wind_heading=90.0))
    assert np.allclose(eq90["U"], 8.0, atol=1e-2)


def test_calc_aep(pseudo_farm):
    rose = [(8.0, 0.0, 0.5), (8.0, 90.0, 0.5)]
    out = calc_aep(pseudo_farm, rose)
    assert out["AEP"] > 0
    # the aligned state loses power to wakes; the crosswind one does not
    p_aligned = out["states"][0]["farm_power"]
    p_cross = out["states"][1]["farm_power"]
    assert p_aligned < p_cross
    # AEP equals the probability-weighted sum of state powers x hours
    expect = 8760.0 * (0.5 * p_aligned + 0.5 * p_cross)
    assert out["AEP"] == pytest.approx(expect, rel=1e-9)


def test_floris_turbine_dict(pseudo_farm):
    """The FLORIS turbine-library dict builder (no floris needed): keys,
    curve lengths, tilt table monotone-through-rated, and the reference's
    floating flags (raft_model.py:1806-1846)."""
    from raft_tpu.models.wake import floris_turbine_dict

    farm = pseudo_farm
    farm.design = {"site": {"rho_air": 1.225}}
    farm._state = [{} for _ in range(farm.nFOWT)]
    template = dict(power_thrust_table={}, floating_tilt_table={},
                    TSR=9.0)
    uhubs = [5.0, 8.0, 11.0, 14.0, 40.0]          # 40 m/s: parked bin
    td = floris_turbine_dict(farm, 0, template, uhubs=uhubs)
    rot = farm.fowtList[0].rotors[0]
    assert td["rotor_diameter"] == pytest.approx(2 * rot.R_rot)
    assert td["hub_height"] == pytest.approx(rot.hubHt)
    assert td["floating_correct_cp_ct_for_tilt"] is False
    assert td["TSR"] == 9.0                       # template carried over
    ptt = td["power_thrust_table"]
    assert len(ptt["power"]) == len(ptt["thrust"]) == len(
        ptt["wind_speed"]) == len(uhubs)
    # FLORIS v3 schema: 'power' is the power COEFFICIENT (reference
    # writes cp, raft_model.py:1837); beyond cut-out the rotor is parked
    assert all(0 < p < 0.6 for p in ptt["power"][:4])
    assert ptt["power"][4] == 0.0 and ptt["thrust"][4] == 0.0
    ftt = td["floating_tilt_table"]
    assert len(ftt["tilt"]) == len(uhubs)
    assert ftt["tilt"][4] == 0.0                  # parked: no mean tilt
    # mean tilt is positive (thrust pushes the platform) and monotone in
    # the dimensional thrust it derives from (power_thrust_curve's raw
    # thrust; ptt["thrust"] holds the Ct coefficient, per FLORIS schema)
    tilt = np.asarray(ftt["tilt"])
    assert np.all(tilt[:4] > 0)                   # operating bins tilt
    from raft_tpu.models.wake import power_thrust_curve
    thrust = power_thrust_curve(farm, speeds=np.asarray(uhubs),
                                ifowt=0)["thrust"]
    assert np.array_equal(np.argsort(tilt), np.argsort(thrust))


def test_floris_coupling_optional_import(pseudo_farm, tmp_path):
    """floris_coupling drives FlorisInterface when floris is importable
    and raises a clear ImportError pointing at the built-in wake when it
    is not (this environment has no floris — the adapter must fail
    cleanly, not crash)."""
    from raft_tpu.models.wake import floris_available, floris_coupling

    if floris_available():
        pytest.skip("floris installed — adapter exercised elsewhere")
    with pytest.raises(ImportError, match="built-in wake"):
        floris_coupling(pseudo_farm, str(tmp_path / "farm.yaml"), [], str(tmp_path))
