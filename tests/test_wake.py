"""Wake coupling (FLORIS-equivalent): Gaussian deficit, farm equilibrium,
power/thrust curves, AEP (reference: raft_model.py:1674-2022)."""
import dataclasses
import types

import numpy as np
import pytest
import yaml

from raft_tpu.models.wake import (calc_aep, find_wake_equilibrium,
                                  gaussian_deficit, power_thrust_curve,
                                  wake_velocities)


def test_gaussian_deficit_shape():
    # no deficit upstream; decays downstream and crosswind; grows with Ct
    assert gaussian_deficit(-2.0, 0.0, 0.8) == 0.0
    d4 = gaussian_deficit(4.0, 0.0, 0.8)
    d8 = gaussian_deficit(8.0, 0.0, 0.8)
    assert 0 < d8 < d4 < 1
    assert gaussian_deficit(4.0, 2.0, 0.8) < d4
    assert gaussian_deficit(4.0, 0.0, 0.4) < d4


def test_wake_velocities_alignment():
    xy = np.array([[0.0, 0.0], [1000.0, 0.0]])
    D, Ct = 200.0, np.array([0.8, 0.8])
    U = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=0.0)
    assert U[0] == pytest.approx(10.0, abs=1e-6)   # upstream untouched
    assert U[1] < 9.0                               # waked
    # crosswind: both free stream
    U90 = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=90.0)
    assert np.allclose(U90, 10.0, atol=1e-3)
    # reversed wind: roles swap
    U180 = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=180.0)
    assert U180[1] == pytest.approx(10.0, abs=1e-6)
    assert U180[0] < 9.0


@pytest.fixture(scope="module")
def pseudo_farm():
    """Two copies of the OC3 FOWT spaced 8D downwind — avoids the heavy
    farm-yaml build; wake functions only need positions + rotors."""
    from raft_tpu.models.fowt import build_fowt

    design = yaml.safe_load(open("/root/reference/designs/OC3spar.yaml"))
    w = np.arange(0.01, 0.2, 0.01) * 2 * np.pi
    f0 = build_fowt(design, w, depth=200.0)
    D = 2 * f0.rotors[0].R_rot
    f1 = dataclasses.replace(f0, x_ref=8.0 * D)
    return types.SimpleNamespace(nFOWT=2, fowtList=[f0, f1])


def test_power_thrust_curve(pseudo_farm):
    curve = power_thrust_curve(pseudo_farm, speeds=np.arange(4.0, 25.0, 2.0))
    assert np.all(curve["Cp"] > 0) and np.all(curve["Cp"] < 0.6)
    assert np.all(curve["Ct"] > 0)
    # NREL 5MW-class turbine: rated power within a factor ~1.3 of 5 MW
    assert 3.5e6 < curve["power"].max() < 7.0e6
    # below rated, Ct high; far above rated (pitched), Ct drops
    assert curve["Ct"][0] > curve["Ct"][-1]


def test_find_wake_equilibrium(pseudo_farm):
    eq = find_wake_equilibrium(pseudo_farm,
                               dict(wind_speed=8.0, wind_heading=0.0))
    assert eq["U"][0] == pytest.approx(8.0, abs=1e-4)
    assert eq["U"][1] < 7.5                       # waked below free stream
    assert eq["power"][1] < eq["power"][0]
    assert eq["iterations"] < 50
    assert eq["case"]["wind_speed"][1] == pytest.approx(eq["U"][1])
    # crosswind: no wake interaction
    eq90 = find_wake_equilibrium(pseudo_farm,
                                 dict(wind_speed=8.0, wind_heading=90.0))
    assert np.allclose(eq90["U"], 8.0, atol=1e-2)


def test_calc_aep(pseudo_farm):
    rose = [(8.0, 0.0, 0.5), (8.0, 90.0, 0.5)]
    out = calc_aep(pseudo_farm, rose)
    assert out["AEP"] > 0
    # the aligned state loses power to wakes; the crosswind one does not
    p_aligned = out["states"][0]["farm_power"]
    p_cross = out["states"][1]["farm_power"]
    assert p_aligned < p_cross
    # AEP equals the probability-weighted sum of state powers x hours
    expect = 8760.0 * (0.5 * p_aligned + 0.5 * p_cross)
    assert out["AEP"] == pytest.approx(expect, rel=1e-9)
