"""Wake coupling (FLORIS-equivalent): Gaussian deficit, farm equilibrium,
power/thrust curves, AEP (reference: raft_model.py:1674-2022)."""
import dataclasses
import types

import numpy as np
import pytest
import yaml

from raft_tpu.models.wake import (calc_aep, find_wake_equilibrium,
                                  gaussian_deficit, power_thrust_curve,
                                  wake_velocities)


def test_gaussian_deficit_shape():
    # no deficit upstream; decays downstream and crosswind; grows with Ct
    assert gaussian_deficit(-2.0, 0.0, 0.8) == 0.0
    d4 = gaussian_deficit(4.0, 0.0, 0.8)
    d8 = gaussian_deficit(8.0, 0.0, 0.8)
    assert 0 < d8 < d4 < 1
    assert gaussian_deficit(4.0, 2.0, 0.8) < d4
    assert gaussian_deficit(4.0, 0.0, 0.4) < d4


def test_wake_velocities_alignment():
    xy = np.array([[0.0, 0.0], [1000.0, 0.0]])
    D, Ct = 200.0, np.array([0.8, 0.8])
    U = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=0.0)
    assert U[0] == pytest.approx(10.0, abs=1e-6)   # upstream untouched
    assert U[1] < 9.0                               # waked
    # crosswind: both free stream
    U90 = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=90.0)
    assert np.allclose(U90, 10.0, atol=1e-3)
    # reversed wind: roles swap
    U180 = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=180.0)
    assert U180[1] == pytest.approx(10.0, abs=1e-6)
    assert U180[0] < 9.0


@pytest.fixture(scope="module")
def pseudo_farm():
    """Two copies of the OC3 FOWT spaced 8D downwind — avoids the heavy
    farm-yaml build; wake functions only need positions + rotors."""
    from raft_tpu.models.fowt import build_fowt

    design = yaml.safe_load(open("/root/reference/designs/OC3spar.yaml"))
    w = np.arange(0.01, 0.2, 0.01) * 2 * np.pi
    f0 = build_fowt(design, w, depth=200.0)
    D = 2 * f0.rotors[0].R_rot
    f1 = dataclasses.replace(f0, x_ref=8.0 * D)
    return types.SimpleNamespace(nFOWT=2, fowtList=[f0, f1])


def test_power_thrust_curve(pseudo_farm):
    curve = power_thrust_curve(pseudo_farm, speeds=np.arange(4.0, 25.0, 2.0))
    assert np.all(curve["Cp"] > 0) and np.all(curve["Cp"] < 0.6)
    assert np.all(curve["Ct"] > 0)
    # NREL 5MW-class turbine: rated power within a factor ~1.3 of 5 MW
    assert 3.5e6 < curve["power"].max() < 7.0e6
    # below rated, Ct high; far above rated (pitched), Ct drops
    assert curve["Ct"][0] > curve["Ct"][-1]


def test_find_wake_equilibrium(pseudo_farm):
    eq = find_wake_equilibrium(pseudo_farm,
                               dict(wind_speed=8.0, wind_heading=0.0))
    assert eq["U"][0] == pytest.approx(8.0, abs=1e-4)
    assert eq["U"][1] < 7.5                       # waked below free stream
    assert eq["power"][1] < eq["power"][0]
    assert eq["iterations"] < 50
    assert eq["case"]["wind_speed"][1] == pytest.approx(eq["U"][1])
    # crosswind: no wake interaction
    eq90 = find_wake_equilibrium(pseudo_farm,
                                 dict(wind_speed=8.0, wind_heading=90.0))
    assert np.allclose(eq90["U"], 8.0, atol=1e-2)


def test_calc_aep(pseudo_farm):
    rose = [(8.0, 0.0, 0.5), (8.0, 90.0, 0.5)]
    out = calc_aep(pseudo_farm, rose)
    assert out["AEP"] > 0
    # the aligned state loses power to wakes; the crosswind one does not
    p_aligned = out["states"][0]["farm_power"]
    p_cross = out["states"][1]["farm_power"]
    assert p_aligned < p_cross
    # AEP equals the probability-weighted sum of state powers x hours
    expect = 8760.0 * (0.5 * p_aligned + 0.5 * p_cross)
    assert out["AEP"] == pytest.approx(expect, rel=1e-9)


def test_floris_turbine_dict(pseudo_farm):
    """The FLORIS turbine-library dict builder (no floris needed): keys,
    curve lengths, tilt table monotone-through-rated, and the reference's
    floating flags (raft_model.py:1806-1846)."""
    from raft_tpu.models.wake import floris_turbine_dict

    farm = pseudo_farm
    farm.design = {"site": {"rho_air": 1.225}}
    farm._state = [{} for _ in range(farm.nFOWT)]
    template = dict(power_thrust_table={}, floating_tilt_table={},
                    TSR=9.0)
    uhubs = [5.0, 8.0, 11.0, 14.0, 40.0]          # 40 m/s: parked bin
    td = floris_turbine_dict(farm, 0, template, uhubs=uhubs)
    rot = farm.fowtList[0].rotors[0]
    assert td["rotor_diameter"] == pytest.approx(2 * rot.R_rot)
    assert td["hub_height"] == pytest.approx(rot.hubHt)
    assert td["floating_correct_cp_ct_for_tilt"] is False
    assert td["TSR"] == 9.0                       # template carried over
    ptt = td["power_thrust_table"]
    assert len(ptt["power"]) == len(ptt["thrust"]) == len(
        ptt["wind_speed"]) == len(uhubs)
    # FLORIS v3 schema: 'power' is the power COEFFICIENT (reference
    # writes cp, raft_model.py:1837); beyond cut-out the rotor is parked
    assert all(0 < p < 0.6 for p in ptt["power"][:4])
    assert ptt["power"][4] == 0.0 and ptt["thrust"][4] == 0.0
    ftt = td["floating_tilt_table"]
    assert len(ftt["tilt"]) == len(uhubs)
    assert ftt["tilt"][4] == 0.0                  # parked: no mean tilt
    # mean tilt is positive (thrust pushes the platform) and monotone in
    # the dimensional thrust it derives from (power_thrust_curve's raw
    # thrust; ptt["thrust"] holds the Ct coefficient, per FLORIS schema)
    tilt = np.asarray(ftt["tilt"])
    assert np.all(tilt[:4] > 0)                   # operating bins tilt
    from raft_tpu.models.wake import power_thrust_curve
    thrust = power_thrust_curve(farm, speeds=np.asarray(uhubs),
                                ifowt=0)["thrust"]
    assert np.array_equal(np.argsort(tilt), np.argsort(thrust))


def test_floris_coupling_optional_import(pseudo_farm, tmp_path):
    """floris_coupling drives FlorisInterface when floris is importable
    and raises a clear ImportError pointing at the built-in wake when it
    is not (this environment has no floris — the adapter must fail
    cleanly, not crash)."""
    from raft_tpu.models.wake import floris_available, floris_coupling

    if floris_available():
        pytest.skip("floris installed — adapter exercised elsewhere")
    with pytest.raises(ImportError, match="built-in wake"):
        floris_coupling(pseudo_farm, str(tmp_path / "farm.yaml"), [], str(tmp_path))


# ---------------------------------------------------------------------------
# reference-free: broadcast parity, the Ct -> 1 guard, and the device-
# resident jnp twins the batched farm sweep traces (no /root/reference)
# ---------------------------------------------------------------------------

def _synth_curve():
    """Monotone synthetic power/thrust table — enough structure for the
    wake fixed point without touching the BEM rotor."""
    ws = np.linspace(3.0, 25.0, 45)
    Ct = np.clip(0.85 - 0.028 * (ws - 3.0), 0.06, 0.85)
    power = 5.0e6 * np.clip((ws - 3.0) / 8.0, 0.0, 1.0) ** 3
    return {"wind_speed": ws, "Ct": Ct, "power": power}


def _wake_velocities_loop(xy, D, Ct, U_inf, wind_dir_deg=0.0, k_w=0.05):
    """The O(n^2) Python double loop wake_velocities vectorized away —
    kept here as the parity reference (index-order summation)."""
    from raft_tpu.models.wake import _wake_frame

    xy_w = _wake_frame(xy, wind_dir_deg)
    n = len(xy_w)
    D = np.broadcast_to(np.asarray(D, float), (n,))
    U = np.zeros(n)
    for i in range(n):
        ssq = 0.0
        for j in range(n):
            if i == j:
                continue
            x_d = (xy_w[i, 0] - xy_w[j, 0]) / D[j]
            y_d = (xy_w[i, 1] - xy_w[j, 1]) / D[j]
            ssq += float(gaussian_deficit(x_d, y_d, float(Ct[j]),
                                          k_w)) ** 2
        U[i] = U_inf * (1.0 - np.sqrt(ssq))
    return U


def test_wake_velocities_broadcast_matches_pair_loop():
    rng = np.random.default_rng(11)
    n = 7
    xy = np.stack([rng.uniform(0, 4000, n), rng.uniform(-800, 800, n)],
                  axis=1)
    Ct = rng.uniform(0.2, 0.9, n)
    D = rng.uniform(120.0, 250.0, n)       # per-turbine diameters too
    for wd in (0.0, 37.0, 200.0):
        got = wake_velocities(xy, D, Ct, 10.0, wind_dir_deg=wd)
        ref = _wake_velocities_loop(xy, D, Ct, 10.0, wind_dir_deg=wd)
        np.testing.assert_allclose(got, ref, rtol=1e-12, atol=1e-12)


def test_gaussian_deficit_ct_guard():
    """Clip + floor at CT_MAX: bitwise no-op for in-range Ct, finite for
    the Ct >= 1 a raw thrust curve or an optimizer step can produce."""
    from raft_tpu.models.wake import CT_MAX

    # in-range: identical to the unguarded expression
    for ct in (0.2, 0.5, 0.9):
        sq = np.sqrt(1.0 - ct)
        beta = 0.5 * (1.0 + sq) / sq
        sigma_D = 0.05 * 5.0 + 0.25 * np.sqrt(beta)
        want = ((1.0 - np.sqrt(1.0 - ct / (8.0 * sigma_D ** 2)))
                * np.exp(-0.0))
        assert gaussian_deficit(5.0, 0.0, ct) == want
    # at and past the singularity: finite, saturated at the CT_MAX value
    d_max = gaussian_deficit(5.0, 0.0, CT_MAX)
    for ct in (1.0, 1.5, 3.0):
        d = gaussian_deficit(5.0, 0.0, ct)
        assert np.isfinite(d) and d == d_max


def test_gaussian_deficit_jnp_matches_host_and_grad_finite():
    import jax
    import jax.numpy as jnp

    from raft_tpu.models.wake import gaussian_deficit_jnp

    x = np.linspace(-1.0, 12.0, 27)
    y = np.linspace(-3.0, 3.0, 27)
    for ct in (0.1, 0.5, 0.85, 0.96, 1.0, 1.2):
        host = gaussian_deficit(x, y, ct)
        dev = np.asarray(gaussian_deficit_jnp(jnp.asarray(x),
                                              jnp.asarray(y), ct))
        np.testing.assert_allclose(dev, host, rtol=1e-14, atol=1e-14)
    # the guard's whole point: grad stays finite THROUGH Ct -> 1 (jax
    # evaluates both sides of the clip; an unguarded sqrt(1 - Ct) NaNs
    # the cotangent even when the clipped forward value is fine)
    g = jax.grad(lambda c: gaussian_deficit_jnp(5.0, 0.5, c))
    for ct in (0.5, 0.95, 0.96, 1.0, 1.3):
        assert np.isfinite(float(g(ct))), ct
    gx = jax.grad(lambda xx: gaussian_deficit_jnp(xx, 0.0, 0.8))
    assert np.isfinite(float(gx(0.06)))


def _host_equilibrium(xy, D, curve, U_inf, wind_dir, k_w=0.05,
                      max_iter=100, tol=1e-4, relax=0.5):
    """find_wake_equilibrium's exact schedule on a bare curve dict (the
    model-level wrapper needs rotors; the jnp twin pins against this)."""
    from raft_tpu.models.wake import _curve_interp

    n = len(xy)
    U = np.full(n, float(U_inf))
    Ct = np.asarray(_curve_interp(U, curve, "Ct"))
    for it in range(max_iter):
        U_new = wake_velocities(xy, D, Ct, U_inf, wind_dir, k_w)
        if np.max(np.abs(U_new - U)) < tol:
            U = U_new
            break
        U = relax * U + (1.0 - relax) * U_new
        Ct = np.asarray(_curve_interp(U, curve, "Ct"))
    power = np.asarray(_curve_interp(U, curve, "power"))
    return dict(U=U, Ct=Ct, power=power, iterations=it + 1)


def test_wake_equilibrium_jnp_matches_host_fixed_point():
    """The while_loop state machine must reproduce the host loop's
    break semantics exactly: U = U_new kept on convergence, Ct NOT
    re-interpolated — same iterate sequence, same iteration count."""
    import jax.numpy as jnp

    from raft_tpu.models.wake import wake_equilibrium_jnp

    curve = _synth_curve()
    xy = np.array([[0.0, 0.0], [900.0, 60.0], [1800.0, -90.0],
                   [2700.0, 30.0]])
    D = 240.0
    for U_inf, wd in ((10.0, 0.0), (7.5, 15.0), (13.0, -30.0)):
        host = _host_equilibrium(xy, D, curve, U_inf, wd)
        dev = wake_equilibrium_jnp(
            jnp.asarray(xy), D, jnp.asarray(curve["wind_speed"]),
            jnp.asarray(curve["Ct"]), jnp.asarray(curve["power"]),
            U_inf, wd)
        np.testing.assert_allclose(np.asarray(dev["U"]), host["U"],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(np.asarray(dev["Ct"]), host["Ct"],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(np.asarray(dev["power"]),
                                   host["power"], rtol=1e-10, atol=1e-6)
        assert int(dev["iterations"]) == host["iterations"]
    # parked free stream (above cut-out): no thrust, no wake, converges
    # on the first check in both paths
    host = _host_equilibrium(xy, D, curve, 30.0, 0.0)
    dev = wake_equilibrium_jnp(
        jnp.asarray(xy), D, jnp.asarray(curve["wind_speed"]),
        jnp.asarray(curve["Ct"]), jnp.asarray(curve["power"]), 30.0, 0.0)
    assert np.allclose(np.asarray(dev["U"]), 30.0)
    assert int(dev["iterations"]) == host["iterations"] == 1


def test_wake_equilibria_jnp_vmaps_cases():
    import jax.numpy as jnp

    from raft_tpu.models.wake import (wake_equilibria_jnp,
                                      wake_equilibrium_jnp)

    curve = _synth_curve()
    xy = np.array([[0.0, 0.0], [1000.0, 0.0], [2000.0, 0.0]])
    U_inf = np.array([8.0, 10.0, 12.0, 30.0])
    wd = np.array([0.0, 10.0, -20.0, 0.0])
    eq = wake_equilibria_jnp(
        jnp.asarray(xy), 200.0, jnp.asarray(curve["wind_speed"]),
        jnp.asarray(curve["Ct"]), jnp.asarray(curve["power"]),
        U_inf, wd)
    assert np.asarray(eq["U"]).shape == (4, 3)
    assert np.asarray(eq["iterations"]).shape == (4,)
    one = wake_equilibrium_jnp(
        jnp.asarray(xy), 200.0, jnp.asarray(curve["wind_speed"]),
        jnp.asarray(curve["Ct"]), jnp.asarray(curve["power"]),
        float(U_inf[1]), float(wd[1]))
    np.testing.assert_allclose(np.asarray(eq["U"])[1],
                               np.asarray(one["U"]), rtol=1e-12)
    assert int(np.asarray(eq["iterations"])[1]) == int(one["iterations"])
