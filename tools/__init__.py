"""Operator tooling for raft_tpu (obsctl, raftlint, golden gate, forensics).

Plain scripts (``tools/obsctl.py`` etc.) manage ``sys.path`` themselves;
this package marker exists so the AST linter can be invoked as
``python -m tools.raftlint`` from the repository root.
"""
