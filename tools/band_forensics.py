"""Loaded-case band-residual forensics harness.

Scripts the knob-isolation methodology that closed the round-3/4
operating-case wave-band residual (ROUND4_NOTES / ROUND5_NOTES): given a
design YAML and the matching reference ``analyzeCases`` pickle, it

1. runs the full analysis and prints per-channel std relatives and the
   wave-band PSD ratio profile vs the pickle,
2. re-solves ONLY the dynamics with each ingredient of the impedance
   perturbed (the "knobs": C_moor flavor/scale, B_gyro, aero tensors,
   M_struc, A_morison, per-entry C_moor components) and reports how each
   knob moves the residual bins — minutes per knob instead of a full
   re-run,
3. prints the Euler-vs-rotation-vector C_moor difference at the
   equilibrium pose (the round-5 root cause; see
   mooring.coupled_stiffness_rotvec).

Usage:
    python tools/band_forensics.py \
        /root/reference/tests/test_data/OC3spar.yaml \
        /root/reference/tests/test_data/OC3spar_true_analyzeCases.pkl \
        --case 1 --channel pitch

A future band regression replays in minutes: run this, look at which
knob closes/moves the deviating bins, and chase that ingredient.
"""
import argparse
import copy
import pickle

import numpy as np
import yaml

CHANNELS = ["surge", "sway", "heave", "roll", "pitch", "yaw"]


def _psd(model, ifowt, idof):
    from raft_tpu.ops.spectra import get_psd
    Xi = model._state[ifowt]["Xi"]
    sig = Xi[:, idof, :]
    if idof >= 3:
        sig = sig * (180.0 / np.pi)
    return np.asarray(get_psd(sig, model.w[1] - model.w[0], source_axis=0))


def band_report(model, truth, icase, channel, nbins=12):
    """Std relatives for all channels + the worst PSD-ratio bins."""
    ours = model.results["case_metrics"][icase][0]
    ref = truth[icase][0]
    print(f"--- case {icase} std relatives:")
    for ch in CHANNELS:
        o = float(np.squeeze(ours[f"{ch}_std"]))
        r = float(np.squeeze(ref[f"{ch}_std"]))
        rel = abs(o - r) / abs(r) if r else 0.0
        print(f"  {ch}_std  ours={o:.6g} ref={r:.6g} rel={rel:.2e}")
    for ch in ("Tmoor_std", "AxRNA_std", "Mbase_std"):
        o = np.atleast_1d(np.squeeze(ours[ch])).astype(float)
        r = np.atleast_1d(np.squeeze(ref[ch])).astype(float)
        print(f"  {ch} rel={np.abs(o - r).max() / np.abs(r).max():.2e}")
    idof = CHANNELS.index(channel)
    ref_psd = np.asarray(ref[f"{channel}_PSD"])
    psd = _psd(model, 0, idof)
    sel = ref_psd > 1e-3 * ref_psd.max()
    ratio = np.where(sel, psd / np.where(sel, ref_psd, 1.0), np.nan)
    worst = np.argsort(np.abs(np.nan_to_num(ratio - 1.0)))[::-1][:nbins]
    worst = np.sort(worst)
    print(f"--- worst {channel}-PSD bins (w [rad/s], ours/ref):")
    for k in worst:
        print(f"  w={model.w[k]:.3f}  ratio={ratio[k]:.4f}")
    return worst, ref_psd


KNOBS = {
    # name -> (state path mutator, description)
    "C_moor*1.01": (lambda st: st.__setitem__(
        "C_moor", st["C_moor"] * 1.01), "uniform C_moor scale +1%"),
    "C_moor[5,5]*1.01": (lambda st: st["C_moor"].__setitem__(
        (5, 5), st["C_moor"][5, 5] * 1.01), "yaw-yaw stiffness +1%"),
    "C_moor[4,4]*1.01": (lambda st: st["C_moor"].__setitem__(
        (4, 4), st["C_moor"][4, 4] * 1.01), "pitch-pitch stiffness +1%"),
    "B_gyro*1.01": (lambda st: st["turbine"].__setitem__(
        "B_gyro", np.asarray(st["turbine"]["B_gyro"]) * 1.01),
        "gyroscopic damping +1%"),
    "B_aero*1.01": (lambda st: st["turbine"].__setitem__(
        "B_aero", np.asarray(st["turbine"]["B_aero"]) * 1.01),
        "aero damping +1%"),
    "A_aero*1.01": (lambda st: st["turbine"].__setitem__(
        "A_aero", np.asarray(st["turbine"]["A_aero"]) * 1.01),
        "aero added mass +1%"),
    "M_struc*1.001": (lambda st: st["statics"].__setitem__(
        "M_struc", np.asarray(st["statics"]["M_struc"]) * 1.001),
        "structural mass +0.1%"),
    "A_morison*1.005": (lambda st: st["hydro0"].__setitem__(
        "A_hydro_morison",
        np.asarray(st["hydro0"]["A_hydro_morison"]) * 1.005),
        "Morison added mass +0.5%"),
    "C_moor=euler": (None, "Euler-jacobian C_moor instead of rotvec "
                           "(the pre-round-5 convention)"),
}


def knob_scan(model, case, icase, channel, bins, ref_psd):
    """Perturb each knob, re-run ONLY solveDynamics, report bin movement."""
    from raft_tpu.models import mooring as mr
    idof = CHANNELS.index(channel)
    st0 = model._state[0]
    saved = {k: copy.deepcopy(st0[k]) for k in
             ("C_moor", "turbine", "statics", "hydro0")}
    base_psd = _psd(model, 0, idof)
    sel = ref_psd > 1e-3 * ref_psd.max()

    def rms_misfit(psd):
        r = psd[sel] / ref_psd[sel] - 1.0
        return float(np.sqrt(np.mean(r**2)))

    print(f"--- knob scan (misfit = rms of {channel} PSD ratio-1 over the "
          f"significant band; base {rms_misfit(base_psd):.2e}):")
    for name, (mut, desc) in KNOBS.items():
        for k in saved:
            st0[k] = copy.deepcopy(saved[k])
        if mut is None:   # the C_moor flavor knob
            st0["C_moor"] = np.asarray(mr.coupled_stiffness(
                model.fowtList[0].mooring, st0["r6"],
                current=st0.get("moor_current")))
        else:
            mut(st0)
        model.solveDynamics(case)
        psd = _psd(model, 0, idof)
        moved = (psd[bins] - base_psd[bins]) / np.maximum(base_psd[bins],
                                                          1e-30)
        print(f"  {name:18s} ({desc}): misfit {rms_misfit(psd):.2e}, "
              f"worst-bin moves {np.array2string(moved, precision=3)}")
    for k in saved:
        st0[k] = saved[k]
    model.solveDynamics(case)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("design")
    ap.add_argument("pickle")
    ap.add_argument("--case", type=int, default=1)
    ap.add_argument("--channel", default="pitch", choices=CHANNELS)
    args = ap.parse_args()

    from raft_tpu.model import Model
    from raft_tpu.models import mooring as mr

    design = yaml.safe_load(open(args.design))
    truth = pickle.load(open(args.pickle, "rb"))
    m = Model(design)
    m.analyzeCases()

    # re-establish THIS case's statics/dynamics state BEFORE reading Xi:
    # analyzeCases leaves _state at the LAST case.  The replay MUST run
    # the cases in order from 0: the reference's statics consume the
    # PREVIOUS case's heading through the stale hub-transfer quirk
    # (docs/quirks.md), so jumping straight to case i would evaluate the
    # turbine constants with the wrong staleness and shift the wave band
    # by ~10% on its own.  The cross-case state analyzeCases left behind
    # must be dropped first for the same reason: a replayed case 0 would
    # otherwise see the LAST case's stored hub-transfer heading (and, on
    # potSecOrder designs, its mean-drift force) instead of the fresh
    # defaults analyzeCases started from.
    ncases = len(design["cases"]["data"])
    if args.case != ncases - 1:
        for st in m._state:
            st.pop("_stored_heading", None)
            st.pop("F_meandrift", None)
        second_order = any(f.potSecOrder > 0 for f in m.fowtList)
        for ic in range(args.case + 1):
            c = dict(zip(design["cases"]["keys"],
                         design["cases"]["data"][ic]))
            c["iCase"] = ic
            m._iCase = ic
            m.solveStatics(c)
            if second_order:
                # mirror analyzeCases' operating-point re-solve: the
                # dynamics fill F_meandrift, statics re-solve with it,
                # then it is cleared so it cannot leak into the next case
                m.solveDynamics(c)
                m.solveStatics(c)
                for st in m._state:
                    st.pop("F_meandrift", None)
        if not second_order:
            m.solveDynamics(c)

    bins, ref_psd = band_report(m, truth, args.case, args.channel)

    moor = m.fowtList[0].mooring
    if moor is not None:
        r6 = m._state[0]["r6"]
        Ke = np.asarray(mr.coupled_stiffness(moor, r6))
        Kr = np.asarray(mr.coupled_stiffness_rotvec(moor, r6))
        d = np.abs(Ke - Kr) / np.abs(Ke).max()
        print(f"--- C_moor euler-vs-rotvec max entry diff "
              f"{d.max():.2e} of scale (roll/pitch columns; "
              f"zero at unloaded poses)")

    case = dict(zip(design["cases"]["keys"],
                    design["cases"]["data"][args.case]))
    case["iCase"] = args.case
    m._iCase = args.case
    knob_scan(m, case, args.case, args.channel, bins, ref_psd)


if __name__ == "__main__":
    main()
