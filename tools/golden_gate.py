#!/usr/bin/env python
"""Golden-ledger gate: rerun the committed golden configurations under
the ACTIVE solve path and ``obsctl check`` the live ledgers against the
goldens in tests/golden/.

CI runs this with ``RAFT_TPU_PALLAS=1`` on CPU, which forces every
impedance solve through the Pallas kernel in interpret mode — so the
fused VMEM-resident Gauss-Jordan kernel must reproduce the committed
physics within the 1e-6 ledger tolerance before it is allowed anywhere
near hardware.  Run it with the knob unset to gate any other solve-path
change the same way.

Exit codes: 0 = all goldens reproduced, 1 = regression, 2 = bad setup.

Usage::

    RAFT_TPU_PALLAS=1 python tools/golden_gate.py [--tol-rel 1e-6]
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

GOLDEN_DIR = os.path.join(_ROOT, "tests", "golden")
GOLDENS = {
    "OC3spar": os.path.join(GOLDEN_DIR, "oc3spar_coarse.ledger.json"),
    "VolturnUS-S": os.path.join(GOLDEN_DIR, "volturnus_coarse.ledger.json"),
}
#: the coarse grid the goldens were generated on (one load case) — must
#: match tests/test_regression_sentinel.py GOLDEN_FREQ
GOLDEN_FREQ = {"min_freq": 0.02, "max_freq": 0.2}


def _load_obsctl():
    path = os.path.join(_ROOT, "tools", "obsctl.py")
    spec = importlib.util.spec_from_file_location("obsctl", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_coarse(name: str) -> dict:
    """One analyzeCases run of design ``name`` on the golden grid under
    whatever solve path the environment selects; returns the ledger."""
    from raft_tpu.io.designs import load_design
    from raft_tpu.model import Model

    design = load_design(name)
    design.setdefault("settings", {})
    design["settings"].update(GOLDEN_FREQ)
    design["cases"]["data"] = design["cases"]["data"][:1]
    model = Model(design)
    model.analyzeCases()
    return model.last_ledger


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tol-rel", type=float, default=1e-6,
                    help="ledger tolerance (default 1e-6, the sentinel "
                         "standard)")
    ap.add_argument("--only", choices=sorted(GOLDENS),
                    help="gate a single design")
    args = ap.parse_args(argv)

    # solver-health residuals sit at the machine-epsilon noise floor
    # (~1e-15); across solve paths they drift by O(1) relatively while
    # staying at the floor.  The ledger's relative deviation is bounded
    # by 1.0, and a genuine residual explosion (1e-15 -> 1e-3) lands at
    # ~1.0 — so 0.5 admits floor noise but still trips on a blow-up.
    # Every physics metric (RAOs, means, stds, iters, eigen) stays at
    # the strict --tol-rel.
    resid_tols = ["*_residual*=0.5"]

    from raft_tpu.obs import ledger as L

    obsctl = _load_obsctl()
    names = [args.only] if args.only else sorted(GOLDENS)
    from raft_tpu import _config
    print(f"golden gate: solve path RAFT_TPU_PALLAS={_config.pallas_mode()}",
          flush=True)
    worst = 0
    with tempfile.TemporaryDirectory() as td:
        for name in names:
            golden = GOLDENS[name]
            if not os.path.isfile(golden):
                print(f"golden gate: missing golden {golden}",
                      file=sys.stderr)
                return 2
            print(f"golden gate: running {name} (coarse, 1 case)...",
                  flush=True)
            live = L.write_ledger(_run_coarse(name),
                                  os.path.join(td, f"{name}.ledger.json"))
            rc = obsctl.main(["check", "--baseline", golden, live,
                              "--tol-rel", str(args.tol_rel)]
                             + [a for t in resid_tols
                                for a in ("--tol", t)])
            print(f"golden gate: {name} -> "
                  f"{'OK' if rc == 0 else 'REGRESSED'}", flush=True)
            worst = max(worst, rc)
    return worst


if __name__ == "__main__":
    raise SystemExit(main())
