#!/usr/bin/env python
"""obsctl — the cross-run regression sentinel's command line.

Diffs, checks, and trends the artifacts the `raft_tpu.obs` layer writes:
result ledgers (``raft_tpu.ledger/v1`` — content-addressed physics
digests), run manifests (``raft_tpu.run_manifest/v1``), and the
historical bench round files (``BENCH_r0*.json`` / ``MULTICHIP_r0*.json``).

Subcommands::

    obsctl diff A B                 # ledger-vs-ledger or manifest-vs-
                                    # manifest; exit 1 on any regression
    obsctl check --baseline L CUR   # CUR ledger against a golden/baseline
                                    # ledger with per-metric tolerances
    obsctl trend <dir | files...>   # text trend table over a run series
    obsctl selfcheck                # round-trip a synthetic ledger through
                                    # diff/check/trend; exit 1 on failure
    obsctl lint [raftlint args...]  # static JAX/TPU discipline checks
                                    # (tools/raftlint — the compile-time
                                    # sibling of `check`; exit 1 on
                                    # findings, docs/static_analysis.md)

Exit codes: 0 = no regression, 1 = regression (or selfcheck failure),
2 = bad invocation / unreadable input.

Pure stdlib + raft_tpu.obs.ledger — never initializes a JAX backend, so
it is safe to run on a host whose TPU tunnel is wedged.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tpu.obs import ledger as L  # noqa: E402


def _fail(msg: str, code: int = 2):
    print(f"obsctl: {msg}", file=sys.stderr)
    raise SystemExit(code)


def _parse_tols(pairs: list[str]) -> dict:
    """['rao_*=1e-4', 'drag_iters=0'] -> {pattern: tol}."""
    out = {}
    for p in pairs or []:
        if "=" not in p:
            _fail(f"--tol expects PATTERN=TOL, got {p!r}")
        pat, _, tol = p.partition("=")
        try:
            out[pat] = float(tol)
        except ValueError:
            _fail(f"--tol {p!r}: {tol!r} is not a number")
    return out


def _load(path: str) -> tuple[str, dict]:
    try:
        return L.load_any(path)
    except OSError as e:
        _fail(f"{path}: {e.strerror or e}")
    except (ValueError, json.JSONDecodeError) as e:
        _fail(str(e))


# ---------------------------------------------------------------------------
# diff / check
# ---------------------------------------------------------------------------

def cmd_diff(args) -> int:
    kind_a, a = _load(args.a)
    kind_b, b = _load(args.b)
    if kind_a != kind_b:
        _fail(f"cannot diff a {kind_a} against a {kind_b} "
              f"({args.a} vs {args.b})")
    per_metric = _parse_tols(args.tol)
    if kind_a == "ledger":
        report = L.diff(a, b, tol_rel=args.tol_rel, per_metric=per_metric,
                        ignore=tuple(args.ignore or ()))
    else:
        report = L.compare_manifests(
            a, b, tol_rel=args.tol_rel, tol_perf=args.tol_perf,
            per_metric=per_metric,
            ignore=L.DEFAULT_MANIFEST_IGNORE + tuple(args.ignore or ()))
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(L.format_diff(report))
    return 0 if report["ok"] else 1


def cmd_check(args) -> int:
    kind_base, base = _load(args.baseline)
    kind_cur, cur = _load(args.current)
    if kind_base != "ledger" or kind_cur != "ledger":
        _fail("check compares ledgers; use `obsctl diff` for manifests")
    base_problems = L.validate_ledger(base)
    if base_problems:
        # a corrupted/tampered baseline is bad input, not a regression
        _fail("baseline ledger is invalid: " + "; ".join(base_problems))
    problems = L.validate_ledger(cur)
    if problems:
        print("current ledger is invalid:")
        for p in problems:
            print(f"  {p}")
        return 1
    report = L.diff(base, cur, tol_rel=args.tol_rel,
                    per_metric=_parse_tols(args.tol),
                    ignore=tuple(args.ignore or ()))
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(L.format_diff(report))
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# trend
# ---------------------------------------------------------------------------

def _last_json_line(text: str) -> dict | None:
    """The bench round files wrap the bench's single JSON output line in
    a free-text ``tail`` — recover the last parseable JSON object."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _trend_row(path: str, doc: dict) -> dict:
    name = os.path.basename(path)
    schema = doc.get("schema", "")
    if schema == L.SCHEMA:
        return {"file": name, "kind": f"ledger/{doc.get('kind')}",
                "status": "-", "value": len(doc.get("entries", [])),
                "vs_baseline": None,
                "digest": (doc.get("digest") or "")[7:19],
                "when": (doc.get("created_at") or "")[:19]}
    if schema.startswith("raft_tpu.run_manifest/"):
        res = (doc.get("extra") or {}).get("result") or {}
        sc = (doc.get("extra") or {}).get("self_compare") or {}
        status = doc.get("status")
        if sc:
            ok = sc.get("ok")
            status = f"{status}/" + ("n/a" if ok is None
                                     else "ok" if ok else "REGR")
        return {"file": name, "kind": f"manifest/{doc.get('kind')}",
                "status": status, "value": res.get("value"),
                "vs_baseline": res.get("vs_baseline"),
                "digest": f"{doc.get('duration_s', 0) or 0:.1f}s",
                "when": (doc.get("started_at") or "")[:19]}
    if "tail" in doc and ("cmd" in doc or "n" in doc):    # BENCH_r0*.json
        inner = _last_json_line(doc.get("tail", "")) or {}
        status = "ok" if inner.get("ok") else (
            inner.get("reason") or f"rc={doc.get('rc')}")
        return {"file": name, "kind": "bench-round", "status": status,
                "value": inner.get("value"),
                "vs_baseline": inner.get("vs_baseline"),
                "digest": inner.get("unit", "-"), "when": "-"}
    if "n_devices" in doc:                                # MULTICHIP_r0*.json
        status = ("skipped" if doc.get("skipped")
                  else "ok" if doc.get("ok") else f"rc={doc.get('rc')}")
        return {"file": name, "kind": "multichip", "status": status,
                "value": doc.get("n_devices"), "vs_baseline": None,
                "digest": "devices", "when": "-"}
    return {"file": name, "kind": "unknown", "status": "-", "value": None,
            "vs_baseline": None, "digest": "-", "when": "-"}


def _expand_trend_paths(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            entries = [os.path.join(p, f) for f in os.listdir(p)
                       if f.endswith((".manifest.json", ".ledger.json"))
                       or (f.startswith(("BENCH_r", "MULTICHIP_r"))
                           and f.endswith(".json"))]
            entries.sort(key=lambda f: (os.path.getmtime(f), f))
            out.extend(entries)
        else:
            out.append(p)
    return out


_TREND_COLS = ("file", "kind", "status", "value", "vs_baseline", "digest",
               "when")


def cmd_trend(args) -> int:
    paths = _expand_trend_paths(args.paths)
    if not paths:
        _fail("trend: no inputs (empty directory?)")
    rows = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"file": os.path.basename(p), "kind": "unreadable",
                         "status": type(e).__name__, "value": None,
                         "vs_baseline": None, "digest": "-", "when": "-"})
            continue
        rows.append(_trend_row(p, doc))
    if args.json:
        print(json.dumps(rows, indent=1))
        return 0
    cells = [[_fmt(r[c]) for c in _TREND_COLS] for r in rows]
    widths = [max(len(c[i]) for c in cells + [list(_TREND_COLS)])
              for i in range(len(_TREND_COLS))]
    print("  ".join(h.ljust(w) for h, w in zip(_TREND_COLS, widths)))
    print("  ".join("-" * w for w in widths))
    for row in cells:
        print("  ".join(v.ljust(w) for v, w in zip(row, widths)))
    return 0


# ---------------------------------------------------------------------------
# selfcheck
# ---------------------------------------------------------------------------

def cmd_selfcheck(args) -> int:
    """Round-trip a synthetic ledger and manifest pair through every
    sentinel code path; any broken invariant exits 1."""
    import contextlib
    import copy
    import io
    import tempfile

    checks = []

    def check(name, cond):
        checks.append((name, bool(cond)))
        if not cond:
            print(f"selfcheck FAIL: {name}")

    led = L.new_ledger("selfcheck", run_id="self000000a",
                       config={"nCases": 2})
    L.add_entry(led, "case0/fowt0", {"rao_mag_max_surge": 1.2345,
                                     "std_heave": [0.1, 0.2, 0.3],
                                     "drag_iters": 7})
    L.add_entry(led, "case0/system", {"cond_max": 1.5e4,
                                      "statics_iters": 4})
    L.finalize(led)
    check("ledger validates", L.validate_ledger(led) == [])
    check("self-diff ok", L.diff(led, led)["ok"])
    check("self-diff identical", L.diff(led, led)["identical"])

    # a >tolerance numeric drift must be flagged, with the right name
    drifted = copy.deepcopy(led)
    drifted["entries"][0]["metrics"]["rao_mag_max_surge"] *= 1.0 + 1e-3
    drifted["entries"][0]["digest"] = L.digest_metrics(
        drifted["entries"][0]["metrics"])
    drifted["digest"] = None
    L.finalize(drifted)
    rep = L.diff(led, drifted, tol_rel=1e-6)
    check("drift flagged", not rep["ok"] and len(rep["regressions"]) == 1)
    check("drift named",
          rep["regressions"][0]["metric"] == "rao_mag_max_surge")
    check("drift within loose tol ok", L.diff(led, drifted,
                                              tol_rel=1e-2)["ok"])
    check("per-metric tol override",
          L.diff(led, drifted, tol_rel=1e-6,
                 per_metric={"rao_*": 1e-2})["ok"])

    # vanished entries are regressions too
    shrunk = copy.deepcopy(led)
    shrunk["entries"] = shrunk["entries"][:1]
    shrunk["digest"] = None
    L.finalize(shrunk)
    check("removed entry flagged", not L.diff(led, shrunk)["ok"])

    # tampered metrics must fail validation (content addressing)
    tampered = copy.deepcopy(led)
    tampered["entries"][1]["metrics"]["cond_max"] = 1.0
    check("tamper detected",
          any("digest mismatch" in p
              for p in L.validate_ledger(tampered)))

    man_a = {"schema": "raft_tpu.run_manifest/v1", "run_id": "a", "kind":
             "bench", "status": "ok", "duration_s": 10.0,
             "phases": [{"name": "solve", "total_s": 8.0, "calls": 1}],
             "metrics": {"raft_statics_residual_norm": {
                 "kind": "gauge", "series": [
                     {"labels": {"case": "0"}, "value": 1e-8}]}},
             "extra": {"result": {"value": 1000.0, "ok": True}}}
    man_b = copy.deepcopy(man_a)
    man_b["run_id"] = "b"
    man_b["duration_s"] = 11.0                 # wall jitter: within perf tol
    check("manifest self-compare ok",
          L.compare_manifests(man_a, man_b)["ok"])
    man_b["status"] = "failed"
    man_b["extra"]["result"]["value"] = 100.0  # >50% perf regression
    rep = L.compare_manifests(man_a, man_b)
    names = {r["metric"] for r in rep["regressions"]}
    check("manifest status change flagged", "status" in names)
    check("manifest perf collapse flagged",
          "extra:result:value" in names)

    with tempfile.TemporaryDirectory() as td:
        pa = L.write_ledger(copy.deepcopy(led),
                            os.path.join(td, "a.ledger.json"))
        pb = L.write_ledger(drifted, os.path.join(td, "b.ledger.json"))
        kind, loaded = L.load_any(pa)
        check("write/load round trip",
              kind == "ledger" and loaded["digest"] == led["digest"])
        with contextlib.redirect_stdout(io.StringIO()):
            rc_diff = cmd_diff(argparse.Namespace(
                a=pa, b=pb, tol_rel=1e-6, tol_perf=0.5, tol=[],
                ignore=[], json=True))
        check("diff exit path", rc_diff == 1)
        with open(os.path.join(td, "BENCH_r99.json"), "w") as f:
            json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                       "tail": "noise\n" + json.dumps(
                           {"value": 123.0, "vs_baseline": 2.0,
                            "ok": True, "unit": "v/h"})}, f)
        trend_buf = io.StringIO()
        with contextlib.redirect_stdout(trend_buf):
            rc_trend = cmd_trend(argparse.Namespace(paths=[td], json=True))
        check("trend renders",
              rc_trend == 0 and "bench-round" in trend_buf.getvalue())

    n_fail = sum(1 for _, ok in checks if not ok)
    print(f"obsctl selfcheck: {'OK' if not n_fail else 'FAILED'} "
          f"({len(checks) - n_fail}/{len(checks)} checks passed)")
    return 1 if n_fail else 0


# ---------------------------------------------------------------------------
# lint
# ---------------------------------------------------------------------------

def cmd_lint(args) -> int:
    """Shell into the raftlint CLI (tools/raftlint) so one operator
    entry point covers runtime regressions (`check`/`diff`) and static
    contract violations alike.  Arguments pass through verbatim, except
    a relative ``--output`` is resolved against the INVOKER's cwd
    before the child runs from the repo root (module resolution needs
    that cwd; the report should still land where the operator asked)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fwd = list(args.raftlint_args)
    for i, a in enumerate(fwd):
        if a == "--output" and i + 1 < len(fwd):
            fwd[i + 1] = os.path.abspath(fwd[i + 1])
        elif a.startswith("--output="):
            fwd[i] = "--output=" + os.path.abspath(a.split("=", 1)[1])
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raftlint", *fwd], cwd=repo)
    return proc.returncode


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def _add_tol_args(p):
    p.add_argument("--tol-rel", type=float, default=1e-6,
                   help="relative tolerance for numeric metrics "
                        "(default 1e-6)")
    p.add_argument("--tol", action="append", metavar="PATTERN=TOL",
                   help="per-metric tolerance override (fnmatch pattern), "
                        "repeatable")
    p.add_argument("--ignore", action="append", metavar="PATTERN",
                   help="skip metrics matching this fnmatch pattern, "
                        "repeatable")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report as JSON")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `lint` forwards EVERYTHING verbatim (argparse.REMAINDER refuses
    # to swallow leading --options after a subcommand), so short-
    # circuit before argparse sees raftlint's flags
    if argv[:1] == ["lint"]:
        return cmd_lint(argparse.Namespace(raftlint_args=argv[1:]))
    ap = argparse.ArgumentParser(
        prog="obsctl", description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("diff", help="diff two ledgers or two manifests")
    p.add_argument("a", help="baseline ledger/manifest JSON")
    p.add_argument("b", help="current ledger/manifest JSON")
    p.add_argument("--tol-perf", type=float, default=0.5,
                   help="fractional tolerance for wall-time/perf facts in "
                        "manifest mode (default 0.5)")
    _add_tol_args(p)
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("check",
                       help="check a ledger against a baseline/golden")
    p.add_argument("--baseline", required=True,
                   help="baseline (golden) ledger JSON")
    p.add_argument("current", help="ledger JSON to check")
    _add_tol_args(p)
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("trend",
                       help="text trend table over manifests/ledgers/"
                            "bench rounds")
    p.add_argument("paths", nargs="+",
                   help="obs output directory, or JSON files "
                        "(BENCH_r0*.json, *.manifest.json, *.ledger.json)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_trend)

    p = sub.add_parser("selfcheck",
                       help="round-trip a synthetic ledger through "
                            "diff/check/trend")
    p.set_defaults(fn=cmd_selfcheck)

    p = sub.add_parser("lint",
                       help="run the raftlint static discipline checks "
                            "(args pass through to tools/raftlint)")
    p.add_argument("raftlint_args", nargs=argparse.REMAINDER,
                   help="arguments forwarded to `python -m "
                        "tools.raftlint` (e.g. --format json raft_tpu)")
    p.set_defaults(fn=cmd_lint)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
