#!/usr/bin/env python
"""obsctl — the cross-run regression sentinel's command line.

Diffs, checks, and trends the artifacts the `raft_tpu.obs` layer writes:
result ledgers (``raft_tpu.ledger/v1`` — content-addressed physics
digests), run manifests (``raft_tpu.run_manifest/v1``), and the
historical bench round files (``BENCH_r0*.json`` / ``MULTICHIP_r0*.json``).

Subcommands::

    obsctl diff A B                 # ledger-vs-ledger or manifest-vs-
                                    # manifest; exit 1 on any regression
    obsctl check --baseline L CUR   # CUR ledger against a golden/baseline
                                    # ledger with per-metric tolerances
    obsctl trend <dir | files...>   # text trend table over a run series
    obsctl trend --db trend.sqlite  # ... or over the persistent trend
                                    # store every finished run appends to
    obsctl tail RUN.events.jsonl    # live/offline follow of a flight-
                                    # recorder event file with per-case
                                    # progress + ETA (--follow to stream)
    obsctl trace TID --journal-dir D  # assemble one distributed trace
                                    # (serve WAL + event files) into a
                                    # Perfetto-loadable Chrome trace;
                                    # exit 1 on a broken/orphaned trace
    obsctl serve --dir OBS_DIR      # stdlib HTTP endpoint: /metrics
                                    # (Prometheus), /events, /runs,
                                    # /healthz (--smoke: self-scrape)
    obsctl slo [--db|--fixture|--url]  # declarative SLO gate over the
                                    # trend store (or a live /metrics
                                    # page); exit 1 on violation
    obsctl selfcheck                # round-trip a synthetic ledger through
                                    # diff/check/trend; exit 1 on failure
    obsctl lint [raftlint args...]  # static JAX/TPU discipline checks
                                    # (tools/raftlint — the compile-time
                                    # sibling of `check`; exit 1 on
                                    # findings, docs/static_analysis.md)

Exit codes: 0 = no regression, 1 = regression (or SLO violation /
selfcheck failure), 2 = bad invocation / unreadable input.

Pure stdlib + the jax-free half of raft_tpu.obs (ledger, events,
trendstore, metrics) — never initializes a JAX backend, so it is safe
to run on a host whose TPU tunnel is wedged.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tpu.obs import events as E  # noqa: E402
from raft_tpu.obs import ledger as L  # noqa: E402
from raft_tpu.obs import trendstore as T  # noqa: E402


def _fail(msg: str, code: int = 2):
    print(f"obsctl: {msg}", file=sys.stderr)
    raise SystemExit(code)


def _parse_tols(pairs: list[str]) -> dict:
    """['rao_*=1e-4', 'drag_iters=0'] -> {pattern: tol}."""
    out = {}
    for p in pairs or []:
        if "=" not in p:
            _fail(f"--tol expects PATTERN=TOL, got {p!r}")
        pat, _, tol = p.partition("=")
        try:
            out[pat] = float(tol)
        except ValueError:
            _fail(f"--tol {p!r}: {tol!r} is not a number")
    return out


def _load(path: str) -> tuple[str, dict]:
    try:
        return L.load_any(path)
    except OSError as e:
        _fail(f"{path}: {e.strerror or e}")
    except (ValueError, json.JSONDecodeError) as e:
        _fail(str(e))


# ---------------------------------------------------------------------------
# diff / check
# ---------------------------------------------------------------------------

def cmd_diff(args) -> int:
    kind_a, a = _load(args.a)
    kind_b, b = _load(args.b)
    if kind_a != kind_b:
        _fail(f"cannot diff a {kind_a} against a {kind_b} "
              f"({args.a} vs {args.b})")
    per_metric = _parse_tols(args.tol)
    if kind_a == "ledger":
        report = L.diff(a, b, tol_rel=args.tol_rel, per_metric=per_metric,
                        ignore=tuple(args.ignore or ()))
    else:
        report = L.compare_manifests(
            a, b, tol_rel=args.tol_rel, tol_perf=args.tol_perf,
            per_metric=per_metric,
            ignore=L.DEFAULT_MANIFEST_IGNORE + tuple(args.ignore or ()))
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(L.format_diff(report))
    return 0 if report["ok"] else 1


def cmd_check(args) -> int:
    kind_base, base = _load(args.baseline)
    kind_cur, cur = _load(args.current)
    if kind_base != "ledger" or kind_cur != "ledger":
        _fail("check compares ledgers; use `obsctl diff` for manifests")
    base_problems = L.validate_ledger(base)
    if base_problems:
        # a corrupted/tampered baseline is bad input, not a regression
        _fail("baseline ledger is invalid: " + "; ".join(base_problems))
    problems = L.validate_ledger(cur)
    if problems:
        print("current ledger is invalid:")
        for p in problems:
            print(f"  {p}")
        return 1
    report = L.diff(base, cur, tol_rel=args.tol_rel,
                    per_metric=_parse_tols(args.tol),
                    ignore=tuple(args.ignore or ()))
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(L.format_diff(report))
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# trend
# ---------------------------------------------------------------------------

def _last_json_line(text: str) -> dict | None:
    """The bench round files wrap the bench's single JSON output line in
    a free-text ``tail`` — recover the last parseable JSON object."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _trend_row(path: str, doc: dict) -> dict:
    name = os.path.basename(path)
    schema = doc.get("schema", "")
    if schema == L.SCHEMA:
        return {"file": name, "kind": f"ledger/{doc.get('kind')}",
                "status": "-", "value": len(doc.get("entries", [])),
                "vs_baseline": None,
                "digest": (doc.get("digest") or "")[7:19],
                "when": (doc.get("created_at") or "")[:19]}
    if schema.startswith("raft_tpu.run_manifest/"):
        res = (doc.get("extra") or {}).get("result") or {}
        sc = (doc.get("extra") or {}).get("self_compare") or {}
        status = doc.get("status")
        if sc:
            ok = sc.get("ok")
            status = f"{status}/" + ("n/a" if ok is None
                                     else "ok" if ok else "REGR")
        mesh = (doc.get("config") or {}).get("mesh") or {}
        return {"file": name, "kind": f"manifest/{doc.get('kind')}",
                "status": status, "value": res.get("value"),
                "vs_baseline": res.get("vs_baseline"),
                "mesh": mesh.get("topology") if isinstance(mesh, dict)
                else None,
                "digest": f"{doc.get('duration_s', 0) or 0:.1f}s",
                "when": (doc.get("started_at") or "")[:19]}
    if "tail" in doc and ("cmd" in doc or "n" in doc):    # BENCH_r0*.json
        inner = _last_json_line(doc.get("tail", "")) or {}
        status = "ok" if inner.get("ok") else (
            inner.get("reason") or f"rc={doc.get('rc')}")
        return {"file": name, "kind": "bench-round", "status": status,
                "value": inner.get("value"),
                "vs_baseline": inner.get("vs_baseline"),
                "digest": inner.get("unit", "-"), "when": "-"}
    if "n_devices" in doc:                                # MULTICHIP_r0*.json
        status = ("skipped" if doc.get("skipped")
                  else "ok" if doc.get("ok") else f"rc={doc.get('rc')}")
        return {"file": name, "kind": "multichip", "status": status,
                "value": doc.get("n_devices"), "vs_baseline": None,
                "digest": "devices", "when": "-"}
    return {"file": name, "kind": "unknown", "status": "-", "value": None,
            "vs_baseline": None, "digest": "-", "when": "-"}


def _expand_trend_paths(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            entries = [os.path.join(p, f) for f in os.listdir(p)
                       if f.endswith((".manifest.json", ".ledger.json"))
                       or (f.startswith(("BENCH_r", "MULTICHIP_r"))
                           and f.endswith(".json"))]
            entries.sort(key=lambda f: (os.path.getmtime(f), f))
            out.extend(entries)
        else:
            out.append(p)
    return out


#: ``mesh`` renders the ordered axis topology of a partitioned run
#: (e.g. ``cases=2xfreq=4``, from the trend-store mesh facts) — "-"
#: for single-device runs and pre-partition documents
_TREND_COLS = ("file", "kind", "status", "value", "vs_baseline", "mesh",
               "digest", "when")


def _store_trend_rows(db: str, limit: int = None) -> list[dict]:
    """Trend-table rows from the persistent trend store (the
    re-scan-a-directory model's replacement: one SQLite file every
    finished run appended to)."""
    store = T.TrendStore(db)
    out = []
    for r in reversed(store.rows(limit=limit)):      # oldest first
        facts = r.get("facts") or {}
        value = facts.get("s_per_case", r.get("duration_s"))
        out.append({"file": (r.get("run_id") or "")[:12],
                    "kind": f"trend/{r.get('kind')}",
                    "status": r.get("status"), "value": value,
                    "vs_baseline": facts.get("result_vs_baseline"),
                    "mesh": facts.get("mesh"),
                    "digest": f"{len(facts)} facts",
                    "when": (r.get("started_at") or "-")[:19]})
    return out


def _import_snapshot_row(path: str, doc: dict) -> dict | None:
    """A persistent trend-store row backfilled from a committed bench
    snapshot (``BENCH_r0*.json`` / ``MULTICHIP_r0*.json``): the
    statistical regression sentinel needs history that predates the
    store itself.  ``started_at`` is synthesized from the file mtime so
    the store's newest-first ordering matches the snapshot sequence."""
    name = os.path.basename(path)
    run_id = name[:-len(".json")] if name.endswith(".json") else name
    try:
        started = time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(os.path.getmtime(path)))
    except OSError:
        started = None
    if "tail" in doc and ("cmd" in doc or "n" in doc):    # BENCH_r0*.json
        inner = (doc.get("parsed")
                 or _last_json_line(doc.get("tail", "")) or {})
        facts = {"snapshot": name}
        if inner.get("metric") is not None:
            facts["bench_metric"] = str(inner["metric"])
        if isinstance(inner.get("value"), (int, float)):
            facts["result_value"] = float(inner["value"])
        if isinstance(inner.get("vs_baseline"), (int, float)):
            facts["result_vs_baseline"] = float(inner["vs_baseline"])
        ok = not doc.get("rc") and bool(inner.get("ok", True))
        status = "ok" if ok else str(inner.get("reason")
                                     or f"rc={doc.get('rc')}")
        return {"run_id": run_id, "kind": "bench-round", "status": status,
                "started_at": started, "facts": facts}
    if "n_devices" in doc:                                # MULTICHIP_r0*.json
        status = ("skipped" if doc.get("skipped")
                  else "ok" if doc.get("ok") else f"rc={doc.get('rc')}")
        return {"run_id": run_id, "kind": "multichip", "status": status,
                "started_at": started,
                "facts": {"snapshot": name,
                          "n_devices": doc.get("n_devices")}}
    return None


def cmd_trend(args) -> int:
    if getattr(args, "do_import", False):
        if not getattr(args, "db", None):
            _fail("trend --import: --db DB is required")
        if not args.paths:
            _fail("trend --import: no snapshot files given")
        imported, skipped = [], []
        for p in _expand_trend_paths(args.paths):
            try:
                with open(p) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                skipped.append((os.path.basename(p), type(e).__name__))
                continue
            row = _import_snapshot_row(p, doc)
            if row is None:
                skipped.append((os.path.basename(p), "unrecognized"))
                continue
            imported.append(row)
        if not imported:
            _fail("trend --import: no recognizable snapshots "
                  f"({len(skipped)} skipped)")
        T.TrendStore(args.db).append_rows(imported)
        if args.json:
            print(json.dumps({"imported": imported,
                              "skipped": [list(s) for s in skipped]},
                             indent=1))
            return 0
        for row in imported:
            print(f"imported {row['run_id']} kind={row['kind']} "
                  f"status={row['status']} "
                  f"({len(row['facts'])} facts)")
        for name, why in skipped:
            print(f"skipped {name}: {why}")
        print(f"trend --import: {len(imported)} row(s) -> {args.db}")
        return 0
    if getattr(args, "db", None):
        try:
            rows = _store_trend_rows(args.db, limit=args.limit)
        except Exception as e:  # sqlite errors are bad input, not a crash
            _fail(f"trend: cannot read store {args.db}: {e}")
        if not rows:
            _fail(f"trend: store {args.db} has no runs")
    else:
        if not args.paths:
            _fail("trend: no inputs (pass a directory, files, or --db)")
        paths = _expand_trend_paths(args.paths)
        if not paths:
            _fail("trend: no inputs (empty directory?)")
        rows = []
        for p in paths:
            try:
                with open(p) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                rows.append({"file": os.path.basename(p),
                             "kind": "unreadable",
                             "status": type(e).__name__, "value": None,
                             "vs_baseline": None, "digest": "-",
                             "when": "-"})
                continue
            rows.append(_trend_row(p, doc))
    if args.json:
        print(json.dumps(rows, indent=1))
        return 0
    cells = [[_fmt(r.get(c)) for c in _TREND_COLS] for r in rows]
    widths = [max(len(c[i]) for c in cells + [list(_TREND_COLS)])
              for i in range(len(_TREND_COLS))]
    print("  ".join(h.ljust(w) for h, w in zip(_TREND_COLS, widths)))
    print("  ".join("-" * w for w in widths))
    for row in cells:
        print("  ".join(v.ljust(w) for v, w in zip(row, widths)))
    # crash-safety satellite: a killed run's manifest stub stays
    # status="running" forever — count it instead of treating it as a
    # baseline (bench self-compare and `slo` skip non-ok runs already)
    running = sum(1 for r in rows
                  if str(r.get("status", "")).startswith("running"))
    if running:
        print(f"  {running} run(s) still marked running (in flight or "
              "killed) — not comparable baselines")
    return 0


# ---------------------------------------------------------------------------
# regress — statistical trend-regression sentinel
# ---------------------------------------------------------------------------

def cmd_regress(args) -> int:
    """Statistical drift sentinel: compare the newest run of every
    (kind, fingerprint) trend-store group against its own rolling
    median/MAD history (no hand-set thresholds); exit 1 when any
    unwaived numeric fact lands outside the noise band."""
    db = args.db or os.environ.get("RAFT_TPU_TREND_DB")
    if not db:
        _fail("regress: no trend store (pass --db or set "
              "RAFT_TPU_TREND_DB)")
    if not os.path.exists(db):
        _fail(f"regress: store {db} does not exist")
    waivers = []
    if args.waivers:
        try:
            with open(args.waivers) as f:
                loaded = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            _fail(f"regress: cannot read waivers {args.waivers}: {e}")
        waivers = (loaded.get("waivers", [])
                   if isinstance(loaded, dict) else loaded)
        if not isinstance(waivers, list):
            _fail("regress: waivers must be a JSON list (or "
                  '{"waivers": [...]})')
    try:
        rows = T.TrendStore(db).rows(kind=args.kind, limit=args.limit)
    except Exception as e:  # sqlite errors are bad input, not a crash
        _fail(f"regress: cannot read store {db}: {e}")
    if not rows:
        _fail(f"regress: store {db} has no runs")
    rep = T.evaluate_regression(rows, min_history=args.min_history,
                                nsigma=args.nsigma,
                                rel_floor=args.rel_floor,
                                waivers=waivers)
    if args.json:
        print(json.dumps(rep, indent=1, default=str))
        return 0 if rep["ok"] else 1
    for g in rep["groups"]:
        tag = g.get("skipped") or f"{g['facts_checked']} fact(s) checked"
        print(f"group kind={g['kind']} rows={g['rows']}: {tag}")
    for f_ in rep["regressions"]:
        mark = "waived" if f_["waived"] else "REGRESSION"
        print(f"{mark}: {f_['kind']}:{f_['fact']} = {f_['value']:.6g} "
              f"vs median {f_['median']:.6g} "
              f"(band {f_['band']:.3g}, n={f_['n']}, "
              f"run {f_['run_id']})")
    n_bad = sum(1 for f_ in rep["regressions"] if not f_["waived"])
    print(f"obsctl regress: {'OK' if rep['ok'] else 'FAILED'} "
          f"({rep['checked']} fact(s) checked, "
          f"{len(rep['regressions'])} drift(s), {n_bad} unwaived)")
    return 0 if rep["ok"] else 1


# ---------------------------------------------------------------------------
# tail — follow a flight-recorder event file
# ---------------------------------------------------------------------------

def _trace_tag(e: dict) -> str:
    """Slow-path events carry a distributed-trace exemplar — render it
    so the line in `obsctl tail` leads straight to `obsctl trace`."""
    tid = e.get("trace_id")
    if not tid:
        tids = e.get("trace_ids")
        if isinstance(tids, str):
            tids = [x for x in tids.split(",") if x]
        tid = tids[0] if isinstance(tids, (list, tuple)) and tids else None
    return f" trace={str(tid)[:16]}" if tid else ""


def _fmt_event(e: dict) -> str | None:
    """One rendered line per event (None = not rendered by default)."""
    ts = time.strftime("%H:%M:%S", time.localtime(float(e.get("t", 0))))
    t = e.get("type")
    if t == "begin":
        part = f" part {e['part']}" if e.get("part") else ""
        return (f"{ts} begin {e.get('kind')} run {e.get('run_id')} "
                f"pid {e.get('pid')} @{e.get('hostname')}{part}")
    if t == "end":
        return f"{ts} end status={e.get('status')}"
    if t == "case_start":
        return f"{ts} case {e.get('case')}/{e.get('n_cases')} started"
    if t == "case_end":
        tag = ("resumed" if e.get("resumed")
               else "ok" if e.get("ok", True) else "FAILED")
        s = e.get("s")
        dur = f" ({s:.1f}s)" if isinstance(s, (int, float)) else ""
        return f"{ts} case {e.get('case')} {tag}{dur}"
    if t == "quarantine":
        body = {k: v for k, v in e.items()
                if k not in ("seq", "t", "type")}
        return f"{ts} QUARANTINE {json.dumps(body, default=str)}"
    if t == "recovery":
        return (f"{ts} recovery[{e.get('phase')} case={e.get('case')}] "
                f"{e.get('step_from')} -> {e.get('step_to')} "
                f"({e.get('outcome')}) after {e.get('error')}")
    if t == "exec_cache":
        return f"{ts} exec_cache {e.get('event')}"
    if t == "probe":
        return (f"{ts} probe {e.get('probe')} "
                f"{json.dumps(e.get('values', {}), default=str)}")
    if t == "probe_attempt":
        return (f"{ts} tpu-probe #{e.get('index')} "
                f"{e.get('outcome')} ({e.get('message') or '-'})")
    # serving-layer events (raft_tpu/serve — docs/robustness.md)
    if t == "service_start":
        return f"{ts} service start ladder={'->'.join(e.get('ladder') or [])}"
    if t == "service_mode":
        return (f"{ts} MODE {e.get('from')} -> {e.get('to')} "
                f"({e.get('reason')})")
    if t == "admission_reject":
        ra = e.get("retry_after_s")
        hint = f", retry after {ra:.2f}s" if isinstance(
            ra, (int, float)) else ""
        return (f"{ts} admission REJECT ({e.get('reason')}, "
                f"queue {e.get('queue_depth')}{hint})")
    if t == "retry":
        return (f"{ts} retry req {e.get('req')} after {e.get('error')} "
                f"(attempt {e.get('attempt')}, "
                f"backoff {e.get('backoff_s', 0):.3f}s)")
    if t == "watchdog_abandon":
        return (f"{ts} WATCHDOG abandoned batch {e.get('batch_id')} "
                f"(reqs {e.get('reqs')}){_trace_tag(e)}")
    if t == "request_done":
        return (f"{ts} req {e.get('req')} done "
                f"({e.get('latency_s', 0):.2f}s, mode {e.get('mode')}, "
                f"{str(e.get('digest'))[:19]})")
    if t == "request_failed":
        return (f"{ts} req {e.get('req')} FAILED "
                f"({e.get('error')}: {e.get('message')})")
    # result-tier events (serve/resultstore.py — "Result tier")
    if t == "coalesced":
        return (f"{ts} req {e.get('req')} coalesced onto in-flight "
                f"{str(e.get('rdigest'))[:19]}")
    if t == "store_corrupt":
        return f"{ts} STORE corrupt entry ({e.get('reason')}) — re-solve"
    if t == "store_seed_quarantined":
        return (f"{ts} STORE seed quarantined "
                f"{str(e.get('rdigest'))[:19]}")
    if t == "warm_start_rejected":
        return (f"{ts} WARM-START rejected lane {e.get('lane')} "
                f"({e.get('outcome')}: {e.get('detail')})"
                f"{_trace_tag(e)}")
    if t == "statics_warm_rejected":
        return (f"{ts} STATICS warm seed rejected case {e.get('case')} "
                f"(iters {e.get('iters')}; cold re-solve)")
    # learned-read-tier events (serve/surrogate.py — docs/
    # performance.md "Layer 9")
    if t == "surrogate_served":
        audit = " AUDIT-DUE" if e.get("audit") else ""
        return (f"{ts} surrogate served {str(e.get('rdigest'))[:19]} "
                f"tenant {e.get('tenant')} "
                f"(bundle v{e.get('version')} "
                f"{str(e.get('bundle'))[:19]}){audit}")
    if t == "surrogate_audit":
        if e.get("error"):
            return (f"{ts} surrogate AUDIT-ERROR "
                    f"{str(e.get('rdigest'))[:19]} "
                    f"tenant {e.get('tenant')} (re-solve failed)")
        verdict = "ok" if e.get("ok") else "VIOLATION"
        worst = e.get("worst_std_err_over_bound")
        detail = (f", worst err/bound {worst:.2f}"
                  if isinstance(worst, (int, float)) else "")
        return (f"{ts} surrogate audit {verdict} "
                f"{str(e.get('rdigest'))[:19]} "
                f"tenant {e.get('tenant')}{detail}")
    if t == "surrogate_quarantine":
        return (f"{ts} SURROGATE QUARANTINE tenant {e.get('tenant')} "
                f"bundle v{e.get('version')} "
                f"{str(e.get('bundle'))[:19]} — exact serving until "
                f"re-distill")
    # preemption-tolerance events (serve/checkpoint.py — "Preemption &
    # storage")
    if t in ("ckpt_resume", "ckpt_resumed"):
        req = f" req {e['req']}" if e.get("req") is not None else ""
        return (f"{ts} CKPT resume{req} from step {e.get('step')}"
                f"/{e.get('steps')}{_trace_tag(e)}")
    if t == "ckpt_resume_rejected":
        return (f"{ts} CKPT resume rejected (step {e.get('step')}: "
                f"identity/layout mismatch) — fresh start")
    if t == "ckpt_corrupt":
        return (f"{ts} CKPT corrupt @step {e.get('step')} "
                f"({e.get('reason')}) — fall back one segment")
    if t == "storage_degraded":
        return (f"{ts} STORAGE degraded: {e.get('component')} shed "
                f"(ENOSPC/budget){_trace_tag(e)}")
    if t == "storage_recovered":
        return f"{ts} storage recovered: {e.get('component')} re-probing"
    return None


def _print_progress(p: dict):
    bits = [f"run {p['run_id']} ({p['kind']})", f"status={p['status']}"]
    if p["n_cases"] is not None:
        bits.append(f"{p['done']}/{p['n_cases']} cases done")
    if p["failed"]:
        bits.append(f"{p['failed']} failed")
    if p["resumed"]:
        bits.append(f"{p['resumed']} resumed")
    if p["avg_case_s"] is not None:
        bits.append(f"avg {p['avg_case_s']:.1f} s/case")
    if p["eta_s"] is not None:
        bits.append(f"ETA {p['eta_s']:.0f}s")
    if p["probes"]:
        bits.append(f"{p['probes']} probe samples")
    print("-- " + ", ".join(bits))


def cmd_tail(args) -> int:
    path = args.events
    if not os.path.isfile(path):
        _fail(f"tail: no such event file {path}")

    def render(evs):
        for e in evs:
            if e.get("type", "").startswith("span_") and not args.spans:
                continue
            line = _fmt_event(e)
            if line is None and args.spans:
                line = (f"{time.strftime('%H:%M:%S', time.localtime(float(e.get('t', 0))))} "
                        f"{e.get('type')} {e.get('name')}")
            if line:
                print(line, flush=True)

    evs, offset = E.read_incremental(path, 0)
    prog = E.progress(evs)
    if args.json:
        print(json.dumps(E.public_progress(prog), indent=1))
        return 0
    render(evs)
    _print_progress(prog)
    if not args.follow:
        return 0
    # follow mode: parse only appended lines (byte-offset incremental)
    # and fold them into the running progress state — O(new) per poll
    # — until the run's end record lands.  Rotation is detected by the
    # file's inode changing (os.replace swaps it) with a size-shrink
    # fallback for filesystems without stable inodes.
    try:
        ino = os.stat(path).st_ino
    except OSError:
        ino = None
    try:
        while prog["status"] == "running":
            time.sleep(max(0.05, float(args.interval)))
            try:
                st = os.stat(path)
            except OSError:
                continue                                # mid-rotation
            if (ino is not None and st.st_ino != ino) \
                    or st.st_size < offset:
                offset = 0                              # rotated
            ino = st.st_ino
            new, offset = E.read_incremental(path, offset)
            if new:
                render(new)
                prog = E.progress(new, state=prog)
                _print_progress(prog)
    except KeyboardInterrupt:                          # pragma: no cover
        pass
    return 0


# ---------------------------------------------------------------------------
# serve — stdlib HTTP scrape endpoint over metrics / events / trend store
# ---------------------------------------------------------------------------

def _newest_events_file(obs_dir: str) -> str | None:
    try:
        cands = [os.path.join(obs_dir, f) for f in os.listdir(obs_dir)
                 if f.endswith(".events.jsonl")]
    except OSError:
        return None
    return max(cands, key=os.path.getmtime) if cands else None


def _refresh_serve_metrics(db: str | None, obs_dir: str | None):
    """Fold the trend store and the newest in-flight event file into
    this process's registry so /metrics is a LIVE page: run history as
    raft_tpu_trend_* gauges, the active run as raft_tpu_live_*."""
    from raft_tpu.obs import metrics as M

    if db and os.path.isfile(db):
        rows = T.TrendStore(db).rows(limit=500)
        g = M.gauge("raft_tpu_trend_runs",
                    "runs in the trend store by kind and status")
        g.clear()
        counts: dict = {}
        for r in rows:
            key = (r.get("kind") or "-", r.get("status") or "-")
            counts[key] = counts.get(key, 0) + 1
        for (kind, status), n in counts.items():
            g.set(float(n), kind=kind, status=status)
        gp = M.gauge("raft_tpu_trend_s_per_case_p50",
                     "p50 warm per-case seconds over the trend store's "
                     "newest ok runs, by kind")
        gp.clear()
        by_kind: dict = {}
        for r in rows:
            v = (r.get("facts") or {}).get("s_per_case")
            if r.get("status") == "ok" and isinstance(v, (int, float)):
                by_kind.setdefault(r.get("kind") or "-", []).append(
                    float(v))
        for kind, vals in by_kind.items():
            gp.set(T._percentile(vals[:20], 50), kind=kind)
    ev = _newest_events_file(obs_dir) if obs_dir else None
    if ev:
        p = E.progress(E.read(ev))
        live = M.gauge("raft_tpu_live_run",
                       "info gauge (always 1) naming the newest run "
                       "with a flight-recorder file in the obs dir")
        live.clear()
        live.set(1.0, run_id=str(p.get("run_id")),
                 kind=str(p.get("kind")), status=str(p.get("status")))
        for k, name in (("done", "raft_tpu_live_cases_done"),
                        ("failed", "raft_tpu_live_cases_failed"),
                        ("n_cases", "raft_tpu_live_cases_total"),
                        ("probes", "raft_tpu_live_probe_events")):
            g = M.gauge(name,
                        "flight-recorder progress of the newest run "
                        "(see raft_tpu_live_run for its identity)")
            # cleared even when the newest run lacks the field — a
            # caseless run (bench) must not inherit the previous
            # run's case counts on the scrape page
            g.clear()
            if p.get(k) is not None:
                g.set(float(p[k]))


def make_server(port: int, host: str = "127.0.0.1", db: str = None,
                obs_dir: str = None):
    """Build (not start) the scrape server; returns the HTTPServer.
    Routes: /healthz, /metrics (Prometheus text exposition with the
    process-identity header), /runs (trend store JSON), /events (raw
    JSONL tail of the newest — or ?file= named — event file)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qs, urlparse

    from raft_tpu.obs import metrics as M

    M.record_build_info()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):                     # pragma: no cover
            pass

        def _send(self, code: int, body: str, ctype: str):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):                              # noqa: N802
            url = urlparse(self.path)
            q = parse_qs(url.query)
            try:
                if url.path == "/healthz":
                    n_runs = None
                    if db and os.path.isfile(db):
                        n_runs = T.TrendStore(db).count()
                    ev = _newest_events_file(obs_dir) if obs_dir else None
                    self._send(200, json.dumps(
                        {"ok": True, "pid": os.getpid(),
                         "trend_db": db, "trend_runs": n_runs,
                         "events_file": ev}), "application/json")
                elif url.path == "/metrics":
                    _refresh_serve_metrics(db, obs_dir)
                    self._send(200, M.exposition(),
                               "text/plain; version=0.0.4")
                elif url.path == "/runs":
                    if not (db and os.path.isfile(db)):
                        self._send(404, json.dumps(
                            {"error": "no trend store"}),
                            "application/json")
                        return
                    limit = int(q.get("limit", ["50"])[0])
                    self._send(200, json.dumps(
                        T.TrendStore(db).rows(limit=limit), indent=1,
                        default=str), "application/json")
                elif url.path == "/events":
                    # ?file= takes a BASENAME resolved inside the obs
                    # dir only — a scrape endpoint must not be an
                    # arbitrary-file-read service
                    name = q.get("file", [None])[0]
                    if name:
                        if (os.path.basename(name) != name or not obs_dir
                                or ".events.jsonl" not in name):
                            self._send(400, "file must be a "
                                       "*.events.jsonl basename in the "
                                       "obs dir\n", "text/plain")
                            return
                        path = os.path.join(obs_dir, name)
                    else:
                        path = (_newest_events_file(obs_dir)
                                if obs_dir else None)
                    if not path or not os.path.isfile(path):
                        self._send(404, "no event file\n", "text/plain")
                        return
                    n = int(q.get("n", ["200"])[0])
                    with open(path, encoding="utf-8") as f:
                        lines = f.readlines()[-n:]
                    self._send(200, "".join(lines),
                               "application/x-ndjson")
                else:
                    self._send(404, "not found\n", "text/plain")
            # one bad request must not take down the scrape endpoint
            except Exception as exc:  # raftlint: disable=RTL004
                self._send(500, f"{type(exc).__name__}: {exc}\n",
                           "text/plain")

    return ThreadingHTTPServer((host, int(port)), Handler)


def cmd_serve(args) -> int:
    db = args.db or T.db_path() or (
        os.path.join(args.dir, "trend.sqlite") if args.dir else None)
    srv = make_server(args.port, host=args.host, db=db, obs_dir=args.dir)
    host, port = srv.server_address[:2]
    print(f"obsctl serve: http://{host}:{port}/  "
          f"(metrics, events, runs, healthz; trend db: {db or '-'}, "
          f"obs dir: {args.dir or '-'})", flush=True)
    if args.smoke:
        import threading
        import urllib.request

        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://{host}:{port}"
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as r:
                health = json.loads(r.read().decode())
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                metrics_page = r.read().decode()
            ok = (health.get("ok") is True
                  and "raft_tpu_build_info{" in metrics_page
                  and metrics_page.startswith("# raft_tpu exposition"))
            print(f"obsctl serve --smoke: "
                  f"{'OK' if ok else 'FAILED'} (healthz ok={health.get('ok')}, "
                  f"build_info={'present' if 'raft_tpu_build_info{' in metrics_page else 'MISSING'})")
            return 0 if ok else 1
        finally:
            srv.shutdown()
            srv.server_close()
    try:
        srv.serve_forever()
    except KeyboardInterrupt:                          # pragma: no cover
        pass
    finally:
        srv.server_close()
    return 0


# ---------------------------------------------------------------------------
# slo — declarative gates over the trend store / a live metrics page
# ---------------------------------------------------------------------------

def _print_slo(report: dict):
    for r in report["results"]:
        state = ("skip" if r.get("skipped")
                 else "ok" if r["ok"] else "VIOLATION")
        val = "-" if r["value"] is None else f"{r['value']:.6g}"
        print(f"  {state:9s} {r['name']}: {val} {r.get('op')} "
              f"{r.get('threshold')} (n={r.get('n')})")
    print(f"obsctl slo: {'OK' if report['ok'] else 'VIOLATED'} "
          f"({sum(1 for r in report['results'] if not r['ok'])} "
          f"violation(s) over {len(report['results'])} rule(s))")


def cmd_slo(args) -> int:
    rules = None
    if args.rules:
        try:
            with open(args.rules) as f:
                rules = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            _fail(f"slo: cannot read rules {args.rules}: {e}")
        if not isinstance(rules, list):
            _fail("slo: rules file must be a JSON list of rule objects")
    if args.url:
        import urllib.request
        try:
            with urllib.request.urlopen(args.url, timeout=10) as r:
                text = r.read().decode()
        except OSError as e:
            _fail(f"slo: cannot scrape {args.url}: {e}")
        if rules is None:
            _fail("slo: --url needs --rules with metric-based rules")
        report = T.evaluate_metric_rules(T.parse_prometheus(text), rules)
    else:
        rows = []
        if args.fixture:
            for path in args.fixture:
                loaded = T.load_rows(path)
                if not loaded:
                    _fail(f"slo: fixture {path} has no rows")
                rows.extend(loaded)
            # evaluate_slo's window/"last" semantics expect newest-first
            # (what TrendStore.rows returns); fixtures are committed in
            # append (oldest-first) order
            rows.sort(key=lambda r: str(r.get("started_at") or ""),
                      reverse=True)
        else:
            db = args.db or T.db_path()
            if not db or not os.path.isfile(db):
                _fail("slo: no trend store (pass --db, --fixture, or "
                      "set RAFT_TPU_TREND_DB)")
            rows = T.TrendStore(db).rows()
        report = T.evaluate_slo(rows, rules)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        _print_slo(report)
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# trace — assemble one distributed trace from WAL records + events
# ---------------------------------------------------------------------------

def _print_trace(asm: dict, verbose: bool = False):
    spans = asm["spans"]
    t0 = min((s["t0"] for s in spans.values()), default=0.0)
    print(f"trace {asm['trace_id']}: {len(spans)} span(s) across "
          f"{asm['process_tracks']} process track(s), "
          f"{len(asm['batches'])} batch record(s), "
          f"{asm['resume_links']} resume link(s), "
          f"{asm['orphan_spans']} orphan(s), "
          f"{asm['open_spans']} open")
    for sp in sorted(spans.values(), key=lambda s: s["t0"]):
        run_id, pid = sp["proc"]
        dur = (sp["t1"] - sp["t0"]) if sp["t1"] is not None else 0.0
        link = ("root" if not sp["parent_id"]
                else f"<- {str(sp['parent_id'])[:8]}"
                if sp["parent_id"] in spans
                else f"<- {str(sp['parent_id'])[:8]} (unresolved)")
        print(f"  +{sp['t0'] - t0:8.3f}s {dur:7.3f}s "
              f"{str(sp['name']):18s} span={sp['span_id'][:8]} {link:>16s} "
              f"[{run_id} pid {pid}] {sp['status']}")
    if verbose:
        for i in sorted(asm["instants"], key=lambda x: x["t"]):
            print(f"  +{i['t'] - t0:8.3f}s          {i['name']} "
                  f"[{i['proc'][0]} pid {i['proc'][1]}]")


def cmd_trace(args) -> int:
    from raft_tpu.obs import traceview as TV
    dirs = []
    for root in args.journal_dir:
        found = TV.discover_journal_dirs(root)
        if not found:
            _fail(f"trace: no serve journal under {root}")
        dirs.extend(d for d in found if d not in dirs)
    known = TV.trace_ids(dirs)
    if args.list:
        for tid in known:
            print(tid)
        return 0
    if args.all:
        targets = known
        if not targets:
            _fail("trace: no traced admits in the given journals", 1)
    else:
        if not args.trace_id:
            _fail("trace: give a TRACE_ID (or --list / --all)")
        targets = [args.trace_id]

    assembled = [TV.assemble(t, dirs, events_paths=args.events or ())
                 for t in targets]
    ok = all(a["spans"] and a["orphan_spans"] == 0 for a in assembled)
    if args.expect_resume:
        ok = ok and any(a["resume_links"] > 0 for a in assembled)

    if args.out:
        if len(assembled) != 1:
            _fail("trace: --out needs a single TRACE_ID, not --all")
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(TV.chrome_trace(assembled[0]), f)
        print(f"wrote {args.out}")
    if args.trend_db:
        # fold the connectivity verdict into the trend store so the
        # zero-tolerance `trace_orphan_spans` SLO rule sees it
        agg = {"trace_spans": 0, "trace_orphan_spans": 0,
               "trace_resume_links": 0, "trace_open_spans": 0,
               "trace_process_tracks": 0, "trace_count": len(assembled)}
        t_start = None
        for a in assembled:
            facts = TV.summary_facts(a)
            for k in ("trace_spans", "trace_orphan_spans",
                      "trace_resume_links", "trace_open_spans"):
                agg[k] += facts[k]
            agg["trace_process_tracks"] = max(
                agg["trace_process_tracks"], facts["trace_process_tracks"])
            for sp in a["spans"].values():
                t_start = (sp["t0"] if t_start is None
                           else min(t_start, sp["t0"]))
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                              time.gmtime(t_start or 0))
        # status stays "ok" — the row records the measurement, and the
        # zero-tolerance trace_orphan_spans RULE does the gating
        # (evaluate_slo only reads status-ok rows)
        row = T.TrendStore(args.trend_db).append({
            "run_id": f"trace-{targets[0][:12]}",
            "kind": "trace", "status": "ok",
            "started_at": stamp, "finished_at": stamp,
            "extra": {"trace": agg}})
        print(f"trend row appended: {row.get('run_id')} "
              f"orphans={agg['trace_orphan_spans']}")

    if args.json:
        print(json.dumps({
            "ok": ok,
            "traces": [{**TV.summary_facts(a),
                        "trace_id": a["trace_id"],
                        "roots": a["roots"]} for a in assembled],
        }, indent=1))
    else:
        for a in assembled:
            _print_trace(a, verbose=args.verbose)
        verdict = "CONNECTED" if ok else "BROKEN"
        print(f"obsctl trace: {verdict} ({len(assembled)} trace(s), "
              f"{sum(a['orphan_spans'] for a in assembled)} orphan "
              f"span(s)"
              + (", resume link present" if any(
                  a["resume_links"] for a in assembled) else "") + ")")
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# selfcheck
# ---------------------------------------------------------------------------

def cmd_selfcheck(args) -> int:
    """Round-trip a synthetic ledger and manifest pair through every
    sentinel code path; any broken invariant exits 1."""
    import contextlib
    import copy
    import io
    import tempfile

    checks = []

    def check(name, cond):
        checks.append((name, bool(cond)))
        if not cond:
            print(f"selfcheck FAIL: {name}")

    led = L.new_ledger("selfcheck", run_id="self000000a",
                       config={"nCases": 2})
    L.add_entry(led, "case0/fowt0", {"rao_mag_max_surge": 1.2345,
                                     "std_heave": [0.1, 0.2, 0.3],
                                     "drag_iters": 7})
    L.add_entry(led, "case0/system", {"cond_max": 1.5e4,
                                      "statics_iters": 4})
    L.finalize(led)
    check("ledger validates", L.validate_ledger(led) == [])
    check("self-diff ok", L.diff(led, led)["ok"])
    check("self-diff identical", L.diff(led, led)["identical"])

    # a >tolerance numeric drift must be flagged, with the right name
    drifted = copy.deepcopy(led)
    drifted["entries"][0]["metrics"]["rao_mag_max_surge"] *= 1.0 + 1e-3
    drifted["entries"][0]["digest"] = L.digest_metrics(
        drifted["entries"][0]["metrics"])
    drifted["digest"] = None
    L.finalize(drifted)
    rep = L.diff(led, drifted, tol_rel=1e-6)
    check("drift flagged", not rep["ok"] and len(rep["regressions"]) == 1)
    check("drift named",
          rep["regressions"][0]["metric"] == "rao_mag_max_surge")
    check("drift within loose tol ok", L.diff(led, drifted,
                                              tol_rel=1e-2)["ok"])
    check("per-metric tol override",
          L.diff(led, drifted, tol_rel=1e-6,
                 per_metric={"rao_*": 1e-2})["ok"])

    # vanished entries are regressions too
    shrunk = copy.deepcopy(led)
    shrunk["entries"] = shrunk["entries"][:1]
    shrunk["digest"] = None
    L.finalize(shrunk)
    check("removed entry flagged", not L.diff(led, shrunk)["ok"])

    # tampered metrics must fail validation (content addressing)
    tampered = copy.deepcopy(led)
    tampered["entries"][1]["metrics"]["cond_max"] = 1.0
    check("tamper detected",
          any("digest mismatch" in p
              for p in L.validate_ledger(tampered)))

    man_a = {"schema": "raft_tpu.run_manifest/v1", "run_id": "a", "kind":
             "bench", "status": "ok", "duration_s": 10.0,
             "phases": [{"name": "solve", "total_s": 8.0, "calls": 1}],
             "metrics": {"raft_statics_residual_norm": {
                 "kind": "gauge", "series": [
                     {"labels": {"case": "0"}, "value": 1e-8}]}},
             "extra": {"result": {"value": 1000.0, "ok": True}}}
    man_b = copy.deepcopy(man_a)
    man_b["run_id"] = "b"
    man_b["duration_s"] = 11.0                 # wall jitter: within perf tol
    check("manifest self-compare ok",
          L.compare_manifests(man_a, man_b)["ok"])
    man_b["status"] = "failed"
    man_b["extra"]["result"]["value"] = 100.0  # >50% perf regression
    rep = L.compare_manifests(man_a, man_b)
    names = {r["metric"] for r in rep["regressions"]}
    check("manifest status change flagged", "status" in names)
    check("manifest perf collapse flagged",
          "extra:result:value" in names)

    with tempfile.TemporaryDirectory() as td:
        pa = L.write_ledger(copy.deepcopy(led),
                            os.path.join(td, "a.ledger.json"))
        pb = L.write_ledger(drifted, os.path.join(td, "b.ledger.json"))
        kind, loaded = L.load_any(pa)
        check("write/load round trip",
              kind == "ledger" and loaded["digest"] == led["digest"])
        with contextlib.redirect_stdout(io.StringIO()):
            rc_diff = cmd_diff(argparse.Namespace(
                a=pa, b=pb, tol_rel=1e-6, tol_perf=0.5, tol=[],
                ignore=[], json=True))
        check("diff exit path", rc_diff == 1)
        with open(os.path.join(td, "BENCH_r99.json"), "w") as f:
            json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                       "tail": "noise\n" + json.dumps(
                           {"value": 123.0, "vs_baseline": 2.0,
                            "ok": True, "unit": "v/h"})}, f)
        trend_buf = io.StringIO()
        with contextlib.redirect_stdout(trend_buf):
            rc_trend = cmd_trend(argparse.Namespace(paths=[td], json=True))
        check("trend renders",
              rc_trend == 0 and "bench-round" in trend_buf.getvalue())

        # regress import + sentinel round trip: backfill the synthetic
        # bench round into a store, then drive the full exit-code path
        db = os.path.join(td, "trend.sqlite")
        with contextlib.redirect_stdout(io.StringIO()):
            rc_imp = cmd_trend(argparse.Namespace(
                paths=[os.path.join(td, "BENCH_r99.json")], db=db,
                do_import=True, json=False, limit=None))
        check("trend --import ok",
              rc_imp == 0 and T.TrendStore(db).count() == 1)
        with contextlib.redirect_stdout(io.StringIO()):
            rc_reg = cmd_regress(argparse.Namespace(
                db=db, kind=None, limit=None, min_history=3,
                nsigma=4.0, rel_floor=0.05, waivers=None, json=False))
        check("regress single-row history ok", rc_reg == 0)

    # regression-sentinel math: identical-fingerprint history with a
    # clear 2x slowdown must flag; sub-percent noise must not
    def srow(i, v):
        return {"run_id": f"r{i:02d}", "kind": "bench-round",
                "status": "ok",
                "started_at": f"2026-01-{i:02d}T00:00:00",
                "facts": {"bench_metric": "solves/sec",
                          "result_value": v}}
    noisy = [srow(5, 1001.0), srow(4, 999.0), srow(3, 1000.5),
             srow(2, 998.5), srow(1, 1000.0)]
    check("regress passes noise", T.evaluate_regression(noisy)["ok"])
    slow = [srow(6, 500.0)] + noisy[1:]
    rep = T.evaluate_regression(slow)
    check("regress flags 2x slowdown",
          not rep["ok"]
          and rep["regressions"][0]["fact"] == "result_value")
    check("regress waiver silences",
          T.evaluate_regression(
              slow, waivers=["bench-round:result_value"])["ok"])
    check("regress min-history guard",
          T.evaluate_regression(slow[:3])["ok"])
    changed = [srow(6, 500.0)] + noisy[1:]
    changed[0]["facts"]["bench_metric"] = "other metric"
    check("regress fingerprint isolates",
          T.evaluate_regression(changed)["ok"])

    n_fail = sum(1 for _, ok in checks if not ok)
    print(f"obsctl selfcheck: {'OK' if not n_fail else 'FAILED'} "
          f"({len(checks) - n_fail}/{len(checks)} checks passed)")
    return 1 if n_fail else 0


# ---------------------------------------------------------------------------
# lint
# ---------------------------------------------------------------------------

def cmd_lint(args) -> int:
    """Shell into the raftlint CLI (tools/raftlint) so one operator
    entry point covers runtime regressions (`check`/`diff`) and static
    contract violations alike.  Arguments pass through verbatim, except
    a relative ``--output`` is resolved against the INVOKER's cwd
    before the child runs from the repo root (module resolution needs
    that cwd; the report should still land where the operator asked)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fwd = list(args.raftlint_args)
    for i, a in enumerate(fwd):
        if a == "--output" and i + 1 < len(fwd):
            fwd[i + 1] = os.path.abspath(fwd[i + 1])
        elif a.startswith("--output="):
            fwd[i] = "--output=" + os.path.abspath(a.split("=", 1)[1])
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raftlint", *fwd], cwd=repo)
    return proc.returncode


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def _add_tol_args(p):
    p.add_argument("--tol-rel", type=float, default=1e-6,
                   help="relative tolerance for numeric metrics "
                        "(default 1e-6)")
    p.add_argument("--tol", action="append", metavar="PATTERN=TOL",
                   help="per-metric tolerance override (fnmatch pattern), "
                        "repeatable")
    p.add_argument("--ignore", action="append", metavar="PATTERN",
                   help="skip metrics matching this fnmatch pattern, "
                        "repeatable")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report as JSON")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `lint` forwards EVERYTHING verbatim (argparse.REMAINDER refuses
    # to swallow leading --options after a subcommand), so short-
    # circuit before argparse sees raftlint's flags
    if argv[:1] == ["lint"]:
        return cmd_lint(argparse.Namespace(raftlint_args=argv[1:]))
    ap = argparse.ArgumentParser(
        prog="obsctl", description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("diff", help="diff two ledgers or two manifests")
    p.add_argument("a", help="baseline ledger/manifest JSON")
    p.add_argument("b", help="current ledger/manifest JSON")
    p.add_argument("--tol-perf", type=float, default=0.5,
                   help="fractional tolerance for wall-time/perf facts in "
                        "manifest mode (default 0.5)")
    _add_tol_args(p)
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("check",
                       help="check a ledger against a baseline/golden")
    p.add_argument("--baseline", required=True,
                   help="baseline (golden) ledger JSON")
    p.add_argument("current", help="ledger JSON to check")
    _add_tol_args(p)
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("trend",
                       help="text trend table over manifests/ledgers/"
                            "bench rounds, or the persistent trend store")
    p.add_argument("paths", nargs="*",
                   help="obs output directory, or JSON files "
                        "(BENCH_r0*.json, *.manifest.json, *.ledger.json)")
    p.add_argument("--db", help="read the persistent SQLite trend store "
                                "instead of scanning files")
    p.add_argument("--limit", type=int, default=None,
                   help="newest N store rows (--db mode)")
    p.add_argument("--import", dest="do_import", action="store_true",
                   help="backfill committed snapshot files "
                        "(BENCH_r0*.json / MULTICHIP_r0*.json) into the "
                        "--db trend store as history rows")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_trend)

    p = sub.add_parser("regress",
                       help="statistical drift detection over the trend "
                            "store (rolling median/MAD noise bands); "
                            "exit 1 on an unwaived regression")
    p.add_argument("--db", help="trend store path (default: "
                                "RAFT_TPU_TREND_DB)")
    p.add_argument("--kind", help="restrict to one run kind")
    p.add_argument("--limit", type=int, default=None,
                   help="newest N store rows (default: all)")
    p.add_argument("--min-history", type=int, default=3,
                   help="baseline samples required per fact (default 3)")
    p.add_argument("--nsigma", type=float, default=4.0,
                   help="noise-band width in robust sigmas (default 4)")
    p.add_argument("--rel-floor", type=float, default=0.05,
                   help="minimum fractional noise band (default 0.05)")
    p.add_argument("--waivers",
                   help="JSON waiver file: a list of \"fact\" / "
                        "\"kind:fact\" strings or {\"kind\", \"fact\"} "
                        "dicts (or {\"waivers\": [...]})")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_regress)

    p = sub.add_parser("tail",
                       help="follow a flight-recorder event file with "
                            "per-case progress and ETA")
    p.add_argument("events", help="a <kind>_<run_id>.events.jsonl file")
    p.add_argument("--follow", "-f", action="store_true",
                   help="keep polling until the run's end record lands")
    p.add_argument("--interval", type=float, default=0.5,
                   help="poll interval in seconds (default 0.5)")
    p.add_argument("--spans", action="store_true",
                   help="also render span open/close events")
    p.add_argument("--json", action="store_true",
                   help="print the reconstructed progress dict as JSON")
    p.set_defaults(fn=cmd_tail)

    p = sub.add_parser("serve",
                       help="HTTP scrape endpoint: /metrics /events "
                            "/runs /healthz (stdlib http.server)")
    p.add_argument("--port", type=int, default=9464,
                   help="listen port (default 9464; 0 = ephemeral)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--dir", help="obs output directory (event files; "
                                 "default trend db location)")
    p.add_argument("--db", help="trend store path (default: "
                                "RAFT_TPU_TREND_DB or <dir>/trend.sqlite)")
    p.add_argument("--smoke", action="store_true",
                   help="start, self-scrape /healthz + /metrics, assert "
                        "raft_tpu_build_info present, exit (CI smoke)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("slo",
                       help="evaluate declarative SLO rules over the "
                            "trend store (or a live /metrics page); "
                            "exit 1 on violation")
    p.add_argument("--db", help="trend store path (default: "
                                "RAFT_TPU_TREND_DB)")
    p.add_argument("--fixture", action="append",
                   help="JSONL trend-row fixture(s) instead of a store "
                        "(the committed golden-run gate), repeatable")
    p.add_argument("--url", help="scrape a live Prometheus page and "
                                 "evaluate metric-based rules instead")
    p.add_argument("--rules", help="JSON rules file (default: the "
                                   "built-in DEFAULT_SLO_RULES)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser("trace",
                       help="assemble one distributed trace from serve "
                            "WAL records (+ event files) into a "
                            "Perfetto-loadable Chrome trace; exit 1 on "
                            "a broken (orphaned) trace")
    p.add_argument("trace_id", nargs="?",
                   help="32-hex trace id (see `--list`, result "
                        "provenance, or `obsctl tail` exemplars)")
    p.add_argument("--journal-dir", action="append", required=True,
                   help="journal directory or soak tree root "
                        "(primary/mirror/successor are auto-"
                        "discovered), repeatable")
    p.add_argument("--events", action="append",
                   help="flight-recorder .events.jsonl file(s) whose "
                        "trace-tagged events become instants, "
                        "repeatable")
    p.add_argument("--list", action="store_true",
                   help="print the trace ids admitted in the journals")
    p.add_argument("--all", action="store_true",
                   help="assemble and gate EVERY trace in the journals "
                        "(the CI chaos gate)")
    p.add_argument("--expect-resume", action="store_true",
                   help="additionally require a cross-process resume "
                        "link (failover/preemption proof)")
    p.add_argument("--out", help="write the Chrome trace JSON here "
                                 "(single TRACE_ID mode)")
    p.add_argument("--trend-db", help="append the connectivity verdict "
                                      "as a trend-store row (feeds the "
                                      "trace_orphan_spans SLO rule)")
    p.add_argument("--verbose", "-v", action="store_true",
                   help="also print per-trace instants")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("selfcheck",
                       help="round-trip a synthetic ledger through "
                            "diff/check/trend")
    p.set_defaults(fn=cmd_selfcheck)

    p = sub.add_parser("lint",
                       help="run the raftlint static discipline checks "
                            "(args pass through to tools/raftlint)")
    p.add_argument("raftlint_args", nargs=argparse.REMAINDER,
                   help="arguments forwarded to `python -m "
                        "tools.raftlint` (e.g. --format json raft_tpu)")
    p.set_defaults(fn=cmd_lint)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        code = main()
        sys.stdout.flush()
    except BrokenPipeError:
        # `obsctl trace --list | head -1` closes stdout early; that is
        # a normal way to consume list output, not an error.  Re-point
        # stdout at devnull so the interpreter's shutdown flush does
        # not raise a second time.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)
