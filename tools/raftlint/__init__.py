"""raftlint — AST-level JAX/TPU discipline checker for raft_tpu.

Static twins of the repo's runtime contracts (docs/static_analysis.md):

- RTL001 host-transfer escape  <-> obs/transfers.py pinned pull budget
- RTL002 recompile hazard      <-> exec_cache warm-start economics
- RTL003 dtype discipline      <-> precision ladder (ROADMAP item 5)
- RTL004 exception discipline  <-> errors.py taxonomy + recovery ladder
- RTL005 logging discipline    <-> obs logging layer (bare-print guard)

Run ``python -m tools.raftlint [paths...]`` from the repository root, or
``python tools/obsctl.py lint``.  Pure stdlib: safe anywhere, fast
everywhere.
"""
from tools.raftlint.config import (Config, ConfigError, find_root,  # noqa: F401
                                   load_config)
from tools.raftlint.core import (Finding, Report, baseline_doc,  # noqa: F401
                                 format_text, lint, load_baseline)
from tools.raftlint.rules import ALL_RULES, RULES_BY_CODE  # noqa: F401

__all__ = ["Config", "ConfigError", "Finding", "Report", "ALL_RULES",
           "RULES_BY_CODE", "lint", "load_config", "find_root",
           "baseline_doc", "load_baseline", "format_text", "main"]


def main(argv=None) -> int:
    from tools.raftlint.__main__ import main as _main
    return _main(argv)
