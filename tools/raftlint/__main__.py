"""raftlint CLI: ``python -m tools.raftlint [options] [paths...]``.

Exit codes: 0 = clean (after suppressions and baseline), 1 = reported
findings, 2 = bad invocation / unreadable or unparseable input
(including modules the analyzer could not parse — reported as RTL000).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# repository-root invocation without installation (obsctl does the same)
_HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)

from tools.raftlint import config as _config          # noqa: E402
from tools.raftlint import core as _core              # noqa: E402
from tools.raftlint import rules as _rules            # noqa: E402


def _fail(msg: str) -> int:
    print(f"raftlint: {msg}", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="raftlint",
        description="AST-level JAX/TPU discipline checker "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         "[tool.raftlint] paths, else raft_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format on stdout (default text)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="baseline file of grandfathered findings "
                         "(default: [tool.raftlint] baseline; pass an "
                         "empty string to disable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current unsuppressed findings to "
                         "the baseline file and exit 0")
    ap.add_argument("--output", metavar="FILE", default=None,
                    help="also write the report (in --format) to FILE "
                         "(CI artifact)")
    ap.add_argument("--select", metavar="CODES", default=None,
                    help="comma-separated rule codes to run exclusively "
                         "(e.g. RTL005)")
    ap.add_argument("--disable", metavar="CODES", default=None,
                    help="comma-separated rule codes to skip")
    ap.add_argument("--root", metavar="DIR", default=None,
                    help="project root (default: nearest ancestor with "
                         "a pyproject.toml)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in _rules.ALL_RULES:
            print(f"{rule.code}  {rule.name:24s} {rule.summary}")
        return 0

    root = args.root or _config.find_root(
        args.paths[0] if args.paths else os.getcwd())
    try:
        cfg = _config.load_config(root)
    except _config.ConfigError as e:
        return _fail(str(e))

    select = ({c.strip().upper() for c in args.select.split(",")
               if c.strip()} if args.select else None)
    disable = ({c.strip().upper() for c in args.disable.split(",")
                if c.strip()} if args.disable else None)
    try:
        report = _core.lint(paths=args.paths or None, root=root,
                            config=cfg, select=select, disable=disable,
                            baseline_path=args.baseline)
    except FileNotFoundError as e:
        return _fail(str(e))
    except ValueError as e:                 # malformed baseline
        return _fail(str(e))

    if args.write_baseline:
        bl = args.baseline if args.baseline is not None else cfg.baseline
        if not bl:
            return _fail("--write-baseline needs --baseline FILE or a "
                         "configured [tool.raftlint] baseline")
        bl_abs = bl if os.path.isabs(bl) else os.path.join(root, bl)
        # re-baseline everything currently reported (plus what the old
        # baseline still covers — shrink on rewrite only when fixed)
        doc = _core.baseline_doc(report.findings + report.baselined)
        os.makedirs(os.path.dirname(bl_abs) or ".", exist_ok=True)
        with open(bl_abs, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"raftlint: wrote {len(doc['findings'])} baseline "
              f"fingerprint(s) to {bl}")
        return 0

    rendered = (json.dumps(report.to_dict(), indent=1)
                if args.format == "json"
                else _core.format_text(report))
    print(rendered)
    if args.output:
        out_abs = args.output if os.path.isabs(args.output) \
            else os.path.join(os.getcwd(), args.output)
        with open(out_abs, "w") as f:
            f.write(rendered)
            f.write("\n")
    if report.parse_errors:      # broken INPUT, not a contract finding
        return 2
    return 0 if report.ok else 1


if __name__ == "__main__":
    try:
        code = main()
        sys.stdout.flush()
    except BrokenPipeError:
        # `raftlint ... | head` (directly or via `obsctl lint`) closes
        # stdout before the report finishes printing; that is a normal
        # way to skim findings, not an error.  Re-point stdout at
        # devnull so the interpreter's shutdown flush cannot raise a
        # second time under `set -o pipefail`.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)
