"""raftlint configuration: ``[tool.raftlint]`` in pyproject.toml.

Python 3.11+ parses pyproject with :mod:`tomllib`.  On 3.10 (which this
repo still supports in CI) there is no stdlib TOML parser and raftlint
must not grow a dependency, so a minimal line-based fallback parser
covers the subset the ``[tool.raftlint*]`` tables actually use: section
headers, bare/quoted keys, strings, booleans, numbers, and (possibly
multi-line) arrays of those.  Anything fancier (inline tables, dotted
keys, escapes beyond ``\\"``) is out of scope for the config schema and
rejected loudly rather than misread silently.
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

try:                                                  # py >= 3.11
    import tomllib as _toml
except ImportError:                                   # py 3.10 fallback
    _toml = None


class ConfigError(Exception):
    """Unreadable or malformed raftlint configuration."""


# ---------------------------------------------------------------------------
# minimal TOML-subset parser (3.10 fallback)
# ---------------------------------------------------------------------------

_SECTION = re.compile(r"^\[([^\]]+)\]\s*(?:#.*)?$")
_KEYVAL = re.compile(r'^("(?:[^"\\]|\\.)*"|[A-Za-z0-9_-]+)\s*=\s*(.*)$')


def _strip_comment(line: str) -> str:
    """Drop a ``#`` comment that is not inside a double-quoted string."""
    out = []
    in_str = False
    i = 0
    while i < len(line):
        c = line[i]
        if c == '"' and (i == 0 or line[i - 1] != "\\"):
            in_str = not in_str
        elif c == "#" and not in_str:
            break
        out.append(c)
        i += 1
    return "".join(out)


def _parse_scalar(tok: str):
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if tok in ("true", "false"):
        return tok == "true"
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    raise ConfigError(f"unsupported TOML value {tok!r} "
                      "(raftlint fallback parser)")


def _split_array_items(body: str) -> list[str]:
    items, cur, in_str = [], [], False
    for i, c in enumerate(body):
        if c == '"' and (i == 0 or body[i - 1] != "\\"):
            in_str = not in_str
        if c == "," and not in_str:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    items.append("".join(cur))
    return [s for s in (x.strip() for x in items) if s]


def _parse_value(tok: str):
    tok = tok.strip()
    if tok.startswith("["):
        if not tok.endswith("]"):
            raise ConfigError(f"unterminated array in {tok!r}")
        return [_parse_scalar(s) for s in _split_array_items(tok[1:-1])]
    return _parse_scalar(tok)


def _bracket_delta(line: str) -> int:
    """Net ``[``/``]`` count outside double-quoted strings — brackets
    inside string values must not confuse the multi-line-array join."""
    delta = 0
    in_str = False
    for i, c in enumerate(line):
        if c == '"' and (i == 0 or line[i - 1] != "\\"):
            in_str = not in_str
        elif not in_str:
            delta += (c == "[") - (c == "]")
    return delta


def _parse_toml_minimal(text: str) -> dict:
    """Subset parser: only what the [tool.raftlint] schema needs."""
    root: dict = {}
    section = root
    pending_key = None
    pending_parts: list[str] = []
    depth = 0
    for raw in text.splitlines():
        line = _strip_comment(raw).rstrip()
        if pending_key is not None:
            pending_parts.append(line)
            depth += _bracket_delta(line)
            if depth <= 0:
                try:
                    section[pending_key] = _parse_value(
                        " ".join(pending_parts))
                except ConfigError:
                    # a value kind we don't support in a FOREIGN table
                    # (inline tables etc.) — same tolerance as the
                    # single-line path; our own schema never hits this
                    pass
                pending_key = None
                pending_parts = []
            continue
        line = line.strip()
        if not line:
            continue
        m = _SECTION.match(line)
        if m:
            section = root
            for part in m.group(1).strip().split("."):
                part = part.strip().strip('"')
                nxt = section.setdefault(part, {})
                if not isinstance(nxt, dict):
                    raise ConfigError(
                        f"section [{m.group(1)}] collides with a value")
                section = nxt
            continue
        m = _KEYVAL.match(line)
        if not m:
            # unsupported syntax OUTSIDE our tables is fine — we only
            # ever read tool.raftlint.*; inside them it would already
            # have matched.  Skip silently.
            continue
        key = m.group(1).strip('"')
        val = m.group(2).strip()
        if val.startswith("[") and not val.endswith("]"):
            pending_key = key
            pending_parts = [val]
            depth = _bracket_delta(val)
            continue
        try:
            section[key] = _parse_value(val)
        except ConfigError:
            # a value kind we don't support in a foreign table (e.g.
            # an inline table under [project]) — irrelevant to us
            continue
    return root


def _load_pyproject(path: str) -> dict:
    with open(path, "rb") as f:
        data = f.read()
    if _toml is not None:
        try:
            return _toml.loads(data.decode("utf-8"))
        except Exception as e:
            raise ConfigError(f"{path}: {e}") from e
    return _parse_toml_minimal(data.decode("utf-8"))


# ---------------------------------------------------------------------------
# config object
# ---------------------------------------------------------------------------

@dataclass
class Config:
    """Resolved raftlint configuration (defaults + pyproject overrides)."""

    root: str = "."
    #: default lint targets when the CLI gets no paths
    paths: list = field(default_factory=lambda: ["raft_tpu"])
    #: committed baseline of grandfathered findings (None = no baseline)
    baseline: str | None = None
    #: rule codes disabled wholesale
    disable: set = field(default_factory=set)
    #: per-rule option tables, keyed by lowercase rule code
    rule_options: dict = field(default_factory=dict)

    def options(self, code: str) -> dict:
        return self.rule_options.get(code.lower(), {})

    def enabled(self, code: str) -> bool:
        return code.upper() not in self.disable


def find_root(start: str) -> str:
    """Nearest ancestor of ``start`` holding a pyproject.toml (falls
    back to ``start`` itself)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        if os.path.isfile(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start if os.path.isdir(start)
                                   else os.path.dirname(start))
        d = parent


def load_config(root: str) -> Config:
    """Read ``[tool.raftlint]`` from ``root``'s pyproject.toml (all keys
    optional; a missing file or section yields pure defaults)."""
    cfg = Config(root=os.path.abspath(root))
    pp = os.path.join(cfg.root, "pyproject.toml")
    if not os.path.isfile(pp):
        return cfg
    doc = _load_pyproject(pp)
    table = (doc.get("tool") or {}).get("raftlint") or {}
    if not isinstance(table, dict):
        raise ConfigError("[tool.raftlint] must be a table")
    for key, val in table.items():
        if isinstance(val, dict):                      # [tool.raftlint.rtl00x]
            cfg.rule_options[key.lower()] = dict(val)
        elif key == "paths":
            cfg.paths = [str(p) for p in val]
        elif key == "baseline":
            cfg.baseline = str(val) or None
        elif key == "disable":
            cfg.disable = {str(c).upper() for c in val}
        # unknown scalar keys are tolerated (forward compatibility)
    return cfg
