"""raftlint engine: file discovery, suppressions, baseline, reporting.

The analyzer is pure stdlib ``ast`` — it never imports jax or raft_tpu,
so it runs in any environment (pre-commit, CI fail-fast, a host with a
wedged TPU tunnel) in milliseconds.

Finding lifecycle::

    rule emits Finding
      -> inline suppression?   (# raftlint: disable=RTL0xx / # print-ok)
      -> baseline match?       (committed grandfather list)
      -> reported              (nonzero exit)

Suppressions attach to the *reported line* of the finding, mirroring
``noqa`` semantics.  The baseline matches on (rule, path, stripped line
text) with per-fingerprint counts, so findings keep matching when
unrelated edits shift line numbers, and a *new* duplicate of a
baselined pattern still fails.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field

from tools.raftlint.config import Config

BASELINE_SCHEMA = "raftlint.baseline/v1"
REPORT_SCHEMA = "raftlint.report/v1"

#: ``# raftlint: disable`` (all rules) or ``disable=RTL001,RTL004``;
#: free-text justification after the codes is encouraged and ignored.
#: The lookahead rejects ``disabled=...``-style typos outright, and the
#: tail is parsed strictly below so a malformed directive reports the
#: finding instead of silently widening to a blanket suppression.
_SUPPRESS = re.compile(r"#\s*raftlint:\s*disable(?![A-Za-z])([^#]*)")
_SUPPRESS_CODES = re.compile(
    r"^\s*((?:[A-Za-z]+\d+)(?:\s*,\s*[A-Za-z]+\d+)*)")
#: legacy print-guard exemption — honored as an RTL005 suppression alias
_PRINT_OK = re.compile(r"#\s*print-ok\b")


@dataclass
class Finding:
    rule: str
    path: str            # project-root-relative, posix separators
    line: int            # 1-based
    col: int             # 0-based
    message: str
    line_text: str = ""

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "line_text": self.line_text}

    def fingerprint(self) -> str:
        key = f"{self.rule}::{self.path}::{self.line_text.strip()}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]


@dataclass
class Module:
    """One parsed source file handed to every rule."""

    path: str            # absolute
    relpath: str         # root-relative posix
    tree: ast.Module
    lines: list
    #: cross-rule caches (e.g. the RTL001/RTL002 device-function index)
    cache: dict = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.relpath, line=lineno,
                       col=getattr(node, "col_offset", 0), message=message,
                       line_text=self.line_text(lineno).rstrip())


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def suppressions_for(lines: list) -> dict:
    """{lineno: set of suppressed rule codes} — ``{"ALL"}`` for blanket
    ``# raftlint: disable`` comments."""
    out: dict = {}
    for i, line in enumerate(lines, 1):
        if "#" not in line:
            continue
        m = _SUPPRESS.search(line)
        if m:
            tail = (m.group(1) or "").strip()
            if tail.startswith("="):
                cm = _SUPPRESS_CODES.match(tail[1:])
                if cm:     # `disable=` with no codes: malformed, no-op
                    out.setdefault(i, set()).update(
                        c.strip().upper()
                        for c in cm.group(1).split(",") if c.strip())
            elif not tail or not tail[0].isalnum():
                # bare `disable` (optionally followed by a `— reason`):
                # blanket; `disable RTL004` (missing =) is malformed
                # and deliberately does NOT suppress
                out.setdefault(i, set()).add("ALL")
        if _PRINT_OK.search(line):
            out.setdefault(i, set()).add("RTL005")
    return out


def is_suppressed(f: Finding, supp: dict) -> bool:
    codes = supp.get(f.line)
    return bool(codes) and ("ALL" in codes or f.rule.upper() in codes)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> dict:
    """{fingerprint: remaining_count} from a committed baseline file."""
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"{path}: not a {BASELINE_SCHEMA} document")
    out: dict = {}
    for n, rec in enumerate(doc.get("findings", [])):
        if not isinstance(rec, dict) or "rule" not in rec \
                or "path" not in rec:
            raise ValueError(
                f"{path}: baseline finding #{n} must be an object with "
                "'rule' and 'path' keys")
        f = Finding(rule=rec["rule"], path=rec["path"], line=0, col=0,
                    message="", line_text=rec.get("line_text", ""))
        try:
            count = int(rec.get("count", 1))
        except (TypeError, ValueError):
            raise ValueError(f"{path}: baseline finding #{n} has a "
                             f"non-integer count {rec.get('count')!r}")
        out[f.fingerprint()] = out.get(f.fingerprint(), 0) + count
    return out


def baseline_doc(findings: list) -> dict:
    """Serializable baseline covering ``findings`` (for
    ``--write-baseline``)."""
    counts: dict = {}
    for f in findings:
        key = (f.rule, f.path, f.line_text.strip())
        counts[key] = counts.get(key, 0) + 1
    return {"schema": BASELINE_SCHEMA,
            "comment": "grandfathered raftlint findings — shrink, "
                       "never grow (docs/static_analysis.md)",
            "findings": [
                {"rule": r, "path": p, "line_text": t, "count": n}
                for (r, p, t), n in sorted(counts.items())]}


def apply_baseline(findings: list, baseline: dict) -> tuple:
    """Split ``findings`` into (reported, baselined)."""
    remaining = dict(baseline)
    reported, baselined = [], []
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            baselined.append(f)
        else:
            reported.append(f)
    return reported, baselined


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def iter_py_files(paths: list, root: str):
    """Yield absolute paths of .py files under ``paths`` exactly once
    each, even for overlapping arguments like ``raft_tpu
    raft_tpu/model.py`` (files pass through; directories are walked,
    skipping __pycache__/hidden)."""
    seen = set()

    def emit(path):
        key = os.path.realpath(path)
        if key not in seen:
            seen.add(key)
            yield path

    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            yield from emit(ap)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        yield from emit(os.path.join(dirpath, fname))
        else:
            raise FileNotFoundError(f"lint path not found: {p}")


def parse_module(path: str, root: str) -> Module:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    tree = ast.parse(source, filename=path)
    return Module(path=path, relpath=rel, tree=tree,
                  lines=source.splitlines())


@dataclass
class Report:
    findings: list = field(default_factory=list)    # reported (unsuppressed,
    #                                                 unbaselined)
    suppressed: list = field(default_factory=list)
    baselined: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)  # Finding (RTL000)
    checked_files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def all_reported(self) -> list:
        return self.parse_errors + self.findings

    def to_dict(self) -> dict:
        return {"schema": REPORT_SCHEMA, "ok": self.ok,
                "checked_files": self.checked_files,
                "counts": {"reported": len(self.all_reported()),
                           "suppressed": len(self.suppressed),
                           "baselined": len(self.baselined)},
                "findings": [f.to_dict() for f in self.all_reported()],
                "suppressed": [f.to_dict() for f in self.suppressed],
                "baselined": [f.to_dict() for f in self.baselined]}


def lint(paths: list = None, root: str = None, config: Config = None,
         select: set = None, disable: set = None,
         baseline_path: str = None, rules: list = None) -> Report:
    """Run the rule set over ``paths`` and return a :class:`Report`.

    ``select``/``disable`` are rule-code sets layered over the config's
    enable table; ``baseline_path`` overrides the configured baseline
    (pass ``""`` to force no baseline).
    """
    from tools.raftlint import rules as _rules
    from tools.raftlint.config import load_config

    if config is None:
        config = load_config(root or ".")
    root = os.path.abspath(root or config.root)
    paths = list(paths) if paths else list(config.paths)
    active = []
    for rule in (rules if rules is not None else _rules.ALL_RULES):
        code = rule.code.upper()
        if select is not None and code not in {c.upper() for c in select}:
            continue
        if disable is not None and code in {c.upper() for c in disable}:
            continue
        if select is None and not config.enabled(code):
            continue
        active.append(rule)

    report = Report()
    raw: list = []
    for path in iter_py_files(paths, root):
        try:
            mod = parse_module(path, root)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            report.parse_errors.append(Finding(
                rule="RTL000", path=rel,
                line=getattr(e, "lineno", 0) or 0, col=0,
                message=f"unparseable module: {e}"))
            continue
        report.checked_files += 1
        supp = suppressions_for(mod.lines)
        for rule in active:
            for f in rule.check(mod, config.options(rule.code)):
                if is_suppressed(f, supp):
                    report.suppressed.append(f)
                else:
                    raw.append(f)

    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    bl_path = baseline_path if baseline_path is not None \
        else config.baseline
    baseline = {}
    if bl_path:
        ap = bl_path if os.path.isabs(bl_path) else os.path.join(root,
                                                                 bl_path)
        if os.path.isfile(ap):
            baseline = load_baseline(ap)
    report.findings, report.baselined = apply_baseline(raw, baseline)
    return report


# ---------------------------------------------------------------------------
# output
# ---------------------------------------------------------------------------

def format_text(report: Report, rules_by_code: dict = None) -> str:
    out = []
    for f in report.all_reported():
        out.append(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}")
        if f.line_text.strip():
            out.append(f"    {f.line_text.strip()}")
    n = len(report.all_reported())
    out.append(
        f"raftlint: {report.checked_files} files, "
        f"{n} finding{'s' if n != 1 else ''} "
        f"({len(report.suppressed)} suppressed, "
        f"{len(report.baselined)} baselined)")
    return "\n".join(out)
