"""The raftlint rule set — static twins of raft_tpu's runtime contracts.

========  =====================================================  ==============================
code      checks                                                 runtime twin
========  =====================================================  ==============================
RTL001    host-transfer escape inside device code                obs/transfers.py pinned budget
RTL002    recompile hazards (traced branch, static args, jit     exec_cache warm-start economics
          built in hot Python loops)
RTL003    dtype discipline in device-code modules                precision ladder (ROADMAP 5)
RTL004    exception discipline on solve paths                    errors.py taxonomy + recovery
RTL005    bare ``print`` in library code                         obs logging/tracing layer
RTL006    sharding locality: ``with_sharding_constraint`` /      parallel/partition.py rules
          mesh-axis-name literals outside the partition layer
========  =====================================================  ==============================

All rules are stdlib-``ast`` visitors over one parsed module at a time.
Cross-module dataflow is intentionally out of scope: the rules
over-approximate *lexically* (anything defined inside a jitted function
is device code; any name handed to ``jax.jit``/``lax.*`` is a device
function) which keeps them fast, deterministic, and explainable.  Known
limits are documented per rule in docs/static_analysis.md.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field


def _prefix_match(relpath: str, prefixes) -> bool:
    """True when root-relative posix ``relpath`` is one of ``prefixes``
    or lives under a directory prefix."""
    for p in prefixes or ():
        p = str(p).rstrip("/")
        if relpath == p or relpath.startswith(p + "/"):
            return True
    return False


def _dotted(node: ast.AST) -> str:
    """'jax.lax.while_loop' for an Attribute/Name chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _import_aliases(tree: ast.Module) -> dict:
    """Map local alias -> canonical dotted module for plain imports
    (``import numpy as np`` -> {"np": "numpy"}; ``from jax import
    numpy as jnp`` -> {"jnp": "jax.numpy"})."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _canonical(dotted: str, aliases: dict) -> str:
    """Resolve the head of a dotted path through the import aliases:
    ``jnp.zeros`` -> ``jax.numpy.zeros``."""
    if not dotted:
        return dotted
    head, _, rest = dotted.partition(".")
    base = aliases.get(head, head)
    return f"{base}.{rest}" if rest else base


def _aliases(mod) -> dict:
    """Module import aliases, computed once per file (mod.cache)."""
    if "aliases" not in mod.cache:
        mod.cache["aliases"] = _import_aliases(mod.tree)
    return mod.cache["aliases"]


class _ParentedWalk:
    """ast.walk with an ancestor stack (for loop/function containment)."""

    def __init__(self, tree):
        self.parents: dict = {}
        stack = [(tree, None)]
        while stack:
            node, parent = stack.pop()
            self.parents[id(node)] = parent
            for child in ast.iter_child_nodes(node):
                stack.append((child, node))

    def ancestors(self, node):
        p = self.parents.get(id(node))
        while p is not None:
            yield p
            p = self.parents.get(id(p))


# ---------------------------------------------------------------------------
# device-function index (shared by RTL001/RTL002)
# ---------------------------------------------------------------------------

_LAX_TRANSFORMS = {"scan", "while_loop", "cond", "fori_loop", "map",
                   "switch", "associated_scan", "associative_scan"}
_FN_TRANSFORMS = {"vmap", "pmap", "checkpoint", "remat", "grad",
                  "value_and_grad"}


def _is_jit_expr(node: ast.AST) -> bool:
    """Expression that evaluates to a jit transform: ``jax.jit``,
    ``jit``, ``partial(jax.jit, ...)``, ``jax.jit(**opts)`` used as a
    decorator factory."""
    dotted = _dotted(node)
    if dotted and (dotted == "jit" or dotted.endswith(".jit")):
        return True
    if isinstance(node, ast.Call):
        fdot = _dotted(node.func)
        if fdot and (fdot == "jit" or fdot.endswith(".jit")):
            return True            # jax.jit(static_argnums=...) factory
        if fdot in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _jit_static_info(call_or_deco: ast.AST) -> tuple:
    """(static_argnums tuple-or-None, static_argnames tuple-or-None)
    pulled out of a jit call/decorator expression (literals only).
    ``partial(jax.jit, static_argnums=...)`` needs no special case: the
    partial call IS the Call examined, so its keywords are read below."""
    node = call_or_deco
    if not isinstance(node, ast.Call):
        return None, None
    nums = names = None
    for kw in node.keywords:
        if kw.arg == "static_argnums":
            nums = _literal_ints(kw.value)
        elif kw.arg == "static_argnames":
            names = _literal_strs(kw.value)
    return nums, names


def _literal_ints(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return None


def _literal_strs(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return None


@dataclass
class DeviceIndex:
    """Which functions in a module are device (traced) code, and with
    what static-argument exemptions."""

    #: id(FunctionDef|Lambda) -> (static_argnums, static_argnames)
    nodes: dict = field(default_factory=dict)
    #: id -> the AST node itself (nodes holds only statics)
    node_by_id: dict = field(default_factory=dict)
    #: every FunctionDef in the module, by name (marking is by name,
    #: over-approximating shadowed defs)
    defs: dict = field(default_factory=dict)
    walk: _ParentedWalk = None

    def device_functions(self):
        """Yield (fn_node, statics) for every marked function/lambda."""
        for fnid, statics in self.nodes.items():
            yield self.node_by_id[fnid], statics

    def is_device_scope(self, node) -> bool:
        """Node (any AST node) lies lexically inside a device function."""
        if id(node) in self.nodes:
            return True
        for anc in self.walk.ancestors(node):
            if id(anc) in self.nodes:
                return True
        return False

    def owning_device_fn(self, node):
        if id(node) in self.nodes:
            return node
        for anc in self.walk.ancestors(node):
            if id(anc) in self.nodes:
                return anc
        return None


def device_index(mod) -> DeviceIndex:
    """Build (and cache) the module's device-function index."""
    if "device_index" in mod.cache:
        return mod.cache["device_index"]
    tree = mod.tree
    aliases = _aliases(mod)
    idx = DeviceIndex(walk=_ParentedWalk(tree))

    attr_aliases: dict = {}        # "solve.batched" -> "solve_batched"
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            idx.defs.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute) \
                and isinstance(node.value, ast.Name):
            tgt = _dotted(node.targets[0])
            if tgt:
                attr_aliases[tgt] = node.value.id

    marked_names: dict = {}        # name -> (static_nums, static_names)

    def mark_name(name, statics=(None, None)):
        marked_names.setdefault(name, statics)

    def mark_arg(arg, statics=(None, None)):
        if isinstance(arg, ast.Name):
            if arg.id in idx.defs:
                mark_name(arg.id, statics)
            elif arg.id in attr_aliases.values():
                mark_name(arg.id, statics)
        elif isinstance(arg, ast.Lambda):
            idx.nodes[id(arg)] = statics
            idx.node_by_id[id(arg)] = arg
        elif isinstance(arg, ast.Attribute):
            target = attr_aliases.get(_dotted(arg))
            if target:
                mark_name(target, statics)

    for node in ast.walk(tree):
        # decorated defs: @jax.jit / @partial(jax.jit, ...) / @jit(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_jit_expr(deco):
                    idx.nodes[id(node)] = _jit_static_info(deco)
                    idx.node_by_id[id(node)] = node
        elif isinstance(node, ast.Call):
            fdot = _dotted(node.func)
            if _is_jit_expr(node.func) or (
                    fdot and (fdot == "jit" or fdot.endswith(".jit"))):
                statics = _jit_static_info(node)
                for arg in node.args:
                    mark_arg(arg, statics)
            else:
                # resolve through the import aliases so ONLY genuine
                # jax transforms match — a bare `map(...)`/local
                # `cond(...)` must not mark host code as device scope
                canon = _canonical(fdot, aliases) if fdot else ""
                tail = canon.rsplit(".", 1)[-1] if canon else ""
                is_lax = tail in _LAX_TRANSFORMS and (
                    f".{canon}".find(".lax.") >= 0
                    or canon.startswith("lax."))
                is_fn_tf = tail in _FN_TRANSFORMS and \
                    canon.startswith(("jax.", "lax."))
                if is_lax or is_fn_tf:
                    for arg in node.args:
                        mark_arg(arg)

    for name, statics in marked_names.items():
        for d in idx.defs.get(name, []):
            idx.nodes.setdefault(id(d), statics)
            idx.node_by_id.setdefault(id(d), d)

    mod.cache["device_index"] = idx
    return idx


def _static_param_names(fn, statics) -> set:
    """Parameter names exempt from traced-value checks (static under
    jit)."""
    if isinstance(fn, ast.Lambda):
        params = [a.arg for a in fn.args.args]
    else:
        params = [a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)]
    nums, names = statics if statics else (None, None)
    out = set(names or ())
    for i in nums or ():
        if 0 <= i < len(params):
            out.add(params[i])
    return out


def _param_names(fn) -> list:
    if isinstance(fn, ast.Lambda):
        return [a.arg for a in fn.args.args]
    return [a.arg for a in (fn.args.posonlyargs + fn.args.args
                            + fn.args.kwonlyargs)]


# ---------------------------------------------------------------------------
# RTL001 — host-transfer escape
# ---------------------------------------------------------------------------

class RTL001:
    code = "RTL001"
    name = "host-transfer-escape"
    summary = ("device->host pulls inside traced code, raw "
               "jax.device_get outside obs/transfers.py, or host "
               "callbacks outside obs/probes.py")

    _BUILTIN_CASTS = {"float", "int", "bool", "complex"}
    _NP_PULLS = {"asarray", "array"}
    #: the host-callback channel: sanctioned ONLY in obs/probes.py
    #: (the counted probe budget), mirroring device_get/transfers.py
    _CALLBACKS = {"jax.debug.callback", "jax.pure_callback",
                  "jax.experimental.io_callback"}

    def check(self, mod, opts):
        if _prefix_match(mod.relpath, opts.get("sanctioned",
                                               ["raft_tpu/obs/transfers.py"])):
            return
        probe_sanctioned = _prefix_match(
            mod.relpath, opts.get("probe-sanctioned",
                                  ["raft_tpu/obs/probes.py"]))
        aliases = _aliases(mod)
        idx = device_index(mod)

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = _canonical(_dotted(node.func), aliases)
            # raw jax.device_get ANYWHERE in library code: the counted
            # wrapper exists precisely so this never appears raw
            if canon == "jax.device_get":
                yield mod.finding(
                    self.code, node,
                    "raw jax.device_get — route device->host pulls "
                    "through obs.transfers.device_get so they are "
                    "counted against the pinned per-case budget")
                continue
            # raw host callbacks ANYWHERE outside the sanctioned probe
            # module: the probe channel counts its traffic in its own
            # raft_tpu_probe_events_total budget and is the only legal
            # way to stream values out of device code mid-execution
            if not probe_sanctioned and (
                    canon in self._CALLBACKS
                    or (canon.startswith("jax.")
                        and canon.endswith(".io_callback"))):
                yield mod.finding(
                    self.code, node,
                    f"raw {canon.rsplit('.', 1)[-1]} — host callbacks "
                    "are the probe channel's job: use obs.probes.probe "
                    "(obs/probes.py is the only sanctioned "
                    "io_callback/jax.debug.callback site, so probe "
                    "traffic stays on its own counted budget)")
                continue
            if not idx.is_device_scope(node):
                continue
            fn = idx.owning_device_fn(node)
            static = _static_param_names(fn, idx.nodes.get(id(fn)))
            msg = self._transfer_call(node, canon, aliases, static)
            if msg:
                yield mod.finding(self.code, node, msg)

    def _transfer_call(self, node, canon, aliases, static_params):
        fdot = _dotted(node.func)
        # builtin casts force a concrete value => trace-time transfer
        if isinstance(node.func, ast.Name) \
                and node.func.id in self._BUILTIN_CASTS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant):
                return None
            if isinstance(arg, ast.Name) and arg.id in static_params:
                return None
            if self._is_static_shape_expr(arg):
                return None
            return (f"{node.func.id}() on a traced value inside a "
                    "jitted/lax-transformed function forces a host "
                    "transfer at trace time")
        if canon.startswith("numpy.") and \
                canon.split(".")[-1] in self._NP_PULLS:
            return (f"{fdot}() inside device code materializes the "
                    "traced operand on host — keep the math in jnp or "
                    "pull through obs.transfers.device_get outside "
                    "the jit boundary")
        if canon == "jax.device_get" or fdot.endswith(".device_get"):
            return ("device_get inside a traced function — pulls "
                    "belong outside the jit boundary, via "
                    "obs.transfers.device_get")
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                return (".item() inside device code is a blocking "
                        "device->host transfer")
            if node.func.attr == "block_until_ready":
                return (".block_until_ready() inside a traced function "
                        "is a sync point — it belongs to the host "
                        "orchestration layer")
        return None

    @staticmethod
    def _is_static_shape_expr(arg) -> bool:
        """``int(x.shape[0])`` / ``float(len(xs))`` / ``x.ndim`` are
        legal transfer-free trace-time constants — exempt expressions
        mentioning a shape/ndim/size attribute or a len() call (a
        documented over-exemption for mixed expressions; real escapes
        pull array VALUES, which never ride a shape access)."""
        for n in ast.walk(arg):
            if isinstance(n, ast.Attribute) and n.attr in ("shape",
                                                           "ndim",
                                                           "size"):
                return True
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Name) and n.func.id == "len":
                return True
        return False


# ---------------------------------------------------------------------------
# RTL002 — recompile hazard
# ---------------------------------------------------------------------------

class RTL002:
    code = "RTL002"
    name = "recompile-hazard"
    summary = ("Python control flow on traced values, unusable "
               "static_argnums, jit construction in hot loops")

    def check(self, mod, opts):
        idx = device_index(mod)
        yield from self._traced_branches(mod, idx)
        yield from self._static_arg_hazards(mod, idx)
        yield from self._jit_in_loop(mod, idx)

    # --- (a) Python if/while/assert on a traced parameter ---------------
    def _traced_branches(self, mod, idx):
        for fn, statics in idx.device_functions():
            if isinstance(fn, ast.Lambda):
                continue
            params = set(_param_names(fn)) - {"self", "cls"} \
                - _static_param_names(fn, statics)
            if not params:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While, ast.Assert)):
                    continue
                test = node.test
                if self._is_static_test(test):
                    continue
                used = {n.id for n in ast.walk(test)
                        if isinstance(n, ast.Name)}
                hit = used & params
                if hit:
                    kind = {ast.If: "if", ast.While: "while",
                            ast.Assert: "assert"}[type(node)]
                    yield mod.finding(
                        self.code, node,
                        f"Python `{kind}` on traced parameter(s) "
                        f"{sorted(hit)} of jitted function "
                        f"`{getattr(fn, 'name', '<lambda>')}` — "
                        "concretizes the tracer (error) or recompiles "
                        "per value; use lax.cond/jnp.where or mark the "
                        "argument static")

    @staticmethod
    def _is_static_test(test) -> bool:
        """Tests that are legitimately static even on a traced name:
        None-ness and isinstance dispatch (decided at trace time on the
        python structure, not the array values)."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in test.ops):
            return True
        if isinstance(test, ast.Call) and \
                _dotted(test.func) in ("isinstance", "callable",
                                       "hasattr"):
            return True
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return RTL002._is_static_test(test.operand)
        if isinstance(test, ast.BoolOp):
            return all(RTL002._is_static_test(v) for v in test.values)
        return False

    # --- (b) static_argnums/argnames hazards ----------------------------
    def _static_arg_hazards(self, mod, idx):
        for fn, statics in idx.device_functions():
            if isinstance(fn, ast.Lambda) or not statics:
                continue
            nums, names = statics
            params = _param_names(fn)
            defaults = self._defaults_by_name(fn)
            for i in nums or ():
                if i >= len(params) or i < -len(params):
                    yield mod.finding(
                        self.code, fn,
                        f"static_argnums index {i} is out of range for "
                        f"`{fn.name}` ({len(params)} parameters)")
                    continue
                for f in self._unhashable_default(mod, defaults,
                                                  params[i], fn):
                    yield f
            for nm in names or ():
                if nm not in params:
                    yield mod.finding(
                        self.code, fn,
                        f"static_argnames {nm!r} does not name a "
                        f"parameter of `{fn.name}`")
                    continue
                for f in self._unhashable_default(mod, defaults, nm, fn):
                    yield f

    @staticmethod
    def _defaults_by_name(fn) -> dict:
        args = fn.args.posonlyargs + fn.args.args
        out = {}
        for a, d in zip(args[len(args) - len(fn.args.defaults):],
                        fn.args.defaults):
            out[a.arg] = d
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if d is not None:
                out[a.arg] = d
        return out

    def _unhashable_default(self, mod, defaults, name, fn):
        d = defaults.get(name)
        if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and _dotted(d.func) in ("list", "dict", "set")):
            yield mod.finding(
                self.code, d,
                f"parameter {name!r} of `{fn.name}` is marked static "
                "but defaults to an unhashable "
                f"{type(d).__name__.lower()} — jit will raise at call "
                "time; use a tuple/frozen value")

    # --- (c) jit built inside a Python loop -----------------------------
    def _jit_in_loop(self, mod, idx):
        for node in ast.walk(mod.tree):
            # direct jit construction only (func is jax.jit/jit itself);
            # the immediate application `jax.jit(f)(x)` must not count
            # the outer call a second time
            if not isinstance(node, ast.Call):
                continue
            fdot = _dotted(node.func)
            if not (fdot and (fdot == "jit" or fdot.endswith(".jit"))):
                continue
            for anc in idx.walk.ancestors(node):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    break      # loop must be in the SAME function scope
                if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                    yield mod.finding(
                        self.code, node,
                        "jax.jit constructed inside a Python loop — a "
                        "fresh wrapper (and, for bound methods/new "
                        "closures, a fresh trace+compile) every "
                        "iteration; hoist the jit out of the loop or "
                        "cache the compiled callable")
                    break


# ---------------------------------------------------------------------------
# RTL003 — dtype discipline
# ---------------------------------------------------------------------------

class RTL003:
    code = "RTL003"
    name = "dtype-discipline"
    summary = ("dtype-less jnp constructors / hard numpy dtype literals "
               "in device-code modules")

    #: constructor -> index of the dtype positional parameter
    _CTORS = {"zeros": 1, "ones": 1, "empty": 1, "arange": 3,
              "linspace": 5}
    _NP_LITERALS = {"float64", "float32", "float16", "complex128",
                    "complex64"}

    def check(self, mod, opts):
        device_modules = opts.get("device-modules",
                                  ["raft_tpu/ops", "raft_tpu/parallel",
                                   "raft_tpu/model.py",
                                   "raft_tpu/models/qtf.py"])
        if not _prefix_match(mod.relpath, device_modules):
            return
        aliases = _aliases(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                canon = _canonical(_dotted(node.func), aliases)
                tail = canon.rsplit(".", 1)[-1]
                if canon.startswith(("jax.numpy.", "jnp.")) \
                        and tail in self._CTORS:
                    if not self._has_dtype(node, self._CTORS[tail]):
                        yield mod.finding(
                            self.code, node,
                            f"jnp.{tail} without an explicit dtype in a "
                            "device-code module — the result silently "
                            "follows the ambient x64 flag; pin it "
                            "(e.g. _config.real_dtype()/complex_dtype(),"
                            " jnp.int32) so the precision ladder stays "
                            "auditable")
                # bare builtin `complex` as a dtype: `.astype(complex)`
                # and `dtype=complex` silently canonicalize per the
                # ambient x64 flag — on the device hot path the complex
                # width must come from _config.complex_dtype() so the
                # precision ladder governs it in one place
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype"
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id == "complex"):
                    yield mod.finding(
                        self.code, node,
                        "bare `.astype(complex)` in a device-code "
                        "module — pin to "
                        "`.astype(_config.complex_dtype())` so the "
                        "precision ladder governs the complex width")
                for kw in node.keywords:
                    if (kw.arg == "dtype"
                            and isinstance(kw.value, ast.Name)
                            and kw.value.id == "complex"):
                        # anchor on the literal itself so multi-line
                        # calls pin/suppress on the line that reads
                        # `dtype=complex`
                        yield mod.finding(
                            self.code, kw.value,
                            "bare `dtype=complex` in a device-code "
                            "module — pin to "
                            "`dtype=_config.complex_dtype()` so the "
                            "precision ladder governs the complex "
                            "width")
            elif isinstance(node, ast.Attribute):
                canon = _canonical(_dotted(node), aliases)
                if canon.startswith("numpy.") and \
                        canon.rsplit(".", 1)[-1] in self._NP_LITERALS:
                    yield mod.finding(
                        self.code, node,
                        f"hard numpy dtype literal `{_dotted(node)}` in "
                        "a device-code module — use the jnp dtype or "
                        "_config.real_dtype()/complex_dtype() so "
                        "precision is governed in one place")

    @staticmethod
    def _has_dtype(call, dtype_pos) -> bool:
        if any(kw.arg == "dtype" for kw in call.keywords):
            return True
        return len(call.args) > dtype_pos


# ---------------------------------------------------------------------------
# RTL004 — exception discipline
# ---------------------------------------------------------------------------

class RTL004:
    code = "RTL004"
    name = "exception-discipline"
    summary = ("non-taxonomy raises on solve paths; broad/bare except "
               "outside the recovery seams")

    _DEFAULT_BANNED_RAISES = [
        "Exception", "BaseException", "RuntimeError", "ValueError",
        "TypeError", "KeyError", "IndexError", "ArithmeticError",
        "FloatingPointError", "ZeroDivisionError", "AssertionError",
        "StopIteration",
    ]
    _BROAD = {"Exception", "BaseException"}

    def check(self, mod, opts):
        solve_modules = opts.get("solve-modules",
                                 ["raft_tpu/model.py", "raft_tpu/ops",
                                  "raft_tpu/parallel", "raft_tpu/io",
                                  "raft_tpu/recovery.py"])
        banned = set(opts.get("raise-banned",
                              self._DEFAULT_BANNED_RAISES)) \
            - set(opts.get("raise-allowed", []))
        if _prefix_match(mod.relpath, solve_modules):
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                name = None
                if isinstance(node.exc, ast.Call) and \
                        isinstance(node.exc.func, ast.Name):
                    name = node.exc.func.id
                elif isinstance(node.exc, ast.Name) and \
                        node.exc.id in banned:
                    # `raise SomeVar` re-raises are fine unless the
                    # name IS a builtin exception class
                    name = node.exc.id
                if name in banned:
                    yield mod.finding(
                        self.code, node,
                        f"raise {name} on a solve path — use the typed "
                        "taxonomy in raft_tpu/errors.py (RaftError "
                        "subclasses carry structured context for the "
                        "recovery ladder, quarantine, and manifests)")
        sanctioned = opts.get("except-sanctioned",
                              ["raft_tpu/recovery.py",
                               "raft_tpu/testing/faults.py"])
        if _prefix_match(mod.relpath, sanctioned):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield mod.finding(
                    self.code, node,
                    "bare `except:` swallows everything including "
                    "KeyboardInterrupt — catch the expected types, or "
                    "move the recovery into the sanctioned "
                    "recovery.py/faults.py seams")
            else:
                names = self._except_names(node.type)
                broad = names & self._BROAD
                if broad:
                    yield mod.finding(
                        self.code, node,
                        f"over-broad `except {'/'.join(sorted(broad))}` "
                        "outside the sanctioned recovery seams — catch "
                        "the expected failure types (see "
                        "errors.RECOVERABLE) so real bugs propagate")

    @staticmethod
    def _except_names(type_node) -> set:
        nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
            else [type_node]
        out = set()
        for n in nodes:
            if isinstance(n, ast.Name):
                out.add(n.id)
            elif isinstance(n, ast.Attribute):
                out.add(n.attr)
        return out


# ---------------------------------------------------------------------------
# RTL005 — logging discipline
# ---------------------------------------------------------------------------

class RTL005:
    code = "RTL005"
    name = "no-bare-print"
    summary = "bare print() in library code (use obs/get_logger)"

    def check(self, mod, opts):
        exempt = opts.get("exempt-files", ["plot.py"])
        base = mod.relpath.rsplit("/", 1)[-1]
        if base in exempt or _prefix_match(mod.relpath, [
                p for p in exempt if "/" in str(p)]):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                yield mod.finding(
                    self.code, node,
                    "bare print() in library code — route output "
                    "through utils.profiling.get_logger / the obs "
                    "layer (tag deliberate report printers with "
                    "`# print-ok`)")


# ---------------------------------------------------------------------------
# RTL006 — sharding locality
# ---------------------------------------------------------------------------

class RTL006:
    """Static twin of the partition-layer contract (PR 8): resharding
    happens at the statics->dynamics boundary inside
    ``parallel/partition.py`` and NOWHERE else.  A stray
    ``with_sharding_constraint`` is an undocumented layout change the
    exec-cache key cannot see; a hardcoded mesh-axis-name string in a
    ``PartitionSpec``/``NamedSharding``/``Mesh`` constructor bypasses
    the rule tables (and their cache-key fingerprint) entirely."""

    code = "RTL006"
    name = "sharding-locality"
    summary = ("with_sharding_constraint / mesh-axis-name literals in "
               "sharding constructors outside parallel/partition.py")

    _DEFAULT_AXIS_NAMES = ["cases", "freq", "variants", "designs"]
    #: constructors whose string arguments name mesh axes
    _CTORS = {"PartitionSpec", "NamedSharding", "Mesh", "AbstractMesh",
              "make_mesh"}

    def check(self, mod, opts):
        sanctioned = opts.get("sanctioned",
                              ["raft_tpu/parallel/partition.py"])
        if _prefix_match(mod.relpath, sanctioned):
            return
        axis_names = set(opts.get("axis-names", self._DEFAULT_AXIS_NAMES))
        aliases = _aliases(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = _canonical(_dotted(node.func), aliases)
            tail = canon.rsplit(".", 1)[-1]
            if tail == "with_sharding_constraint":
                yield mod.finding(
                    self.code, node,
                    "with_sharding_constraint outside "
                    "parallel/partition.py — resharding belongs at the "
                    "statics->dynamics boundary behind "
                    "partition.constrain, where the rule fingerprint "
                    "keys the executable cache")
            elif tail in self._CTORS:
                hit = self._axis_literal(node, axis_names)
                if hit is not None:
                    yield mod.finding(
                        self.code, node,
                        f"mesh-axis-name literal '{hit}' in a {tail} "
                        "constructor outside parallel/partition.py — "
                        "use the partition rule tables / mesh helpers "
                        "so placement stays deliberate and cache-keyed")

    @staticmethod
    def _axis_literal(call: ast.Call, axis_names) -> str | None:
        """First mesh-axis-name string literal among the call's
        argument expressions (tuples/lists included), or None."""
        exprs = list(call.args) + [kw.value for kw in call.keywords]
        for expr in exprs:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str) \
                        and sub.value in axis_names:
                    return sub.value
        return None


# ---------------------------------------------------------------------------
# RTL007 — persistence write-path discipline
# ---------------------------------------------------------------------------

class RTL007:
    """Static twin of the persistence integrity contract (PR 12/15):
    every durable write in a persistence module goes through the ONE
    shared ``tmp -> fsync -> rename`` helper
    (``obs.journalio.fsync_write``) so the sidecar-last / torn-put /
    crash-safety discipline cannot silently fork.  A raw write-mode
    ``open()`` in a checkpoint/result-store/journal module is a write
    path the integrity ladder never audits."""

    code = "RTL007"
    name = "persistence-discipline"
    summary = ("raw write-mode open() in a persistence module outside "
               "the shared tmp->fsync->rename helper")

    _WRITE = set("wax")
    _DEFAULT_MODULES = ["raft_tpu/serve/checkpoint.py",
                        "raft_tpu/serve/resultstore.py",
                        "raft_tpu/serve/journal.py"]
    _DEFAULT_HELPERS = ["fsync_write", "_fsync_write"]

    def check(self, mod, opts):
        modules = opts.get("persistence-modules", self._DEFAULT_MODULES)
        if not _prefix_match(mod.relpath, modules):
            return
        if _prefix_match(mod.relpath, opts.get("sanctioned", [])):
            return
        helpers = set(opts.get("helper-functions",
                               self._DEFAULT_HELPERS))
        walk = _ParentedWalk(mod.tree)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                continue
            mode = None
            if len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" \
                        and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if not (isinstance(mode, str)
                    and (set(mode) & self._WRITE)):
                continue                 # read-mode / dynamic: fine
            fn = next((a for a in walk.ancestors(node)
                       if isinstance(a, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
            if fn is not None and fn.name in helpers:
                continue                 # the shared helper itself
            yield mod.finding(
                self.code, node,
                f"write-mode open({mode!r}) in a persistence module "
                "outside the shared tmp->fsync->rename helper — route "
                "durable writes through obs.journalio.fsync_write "
                "(per-writer tmp, fsync, atomic rename, sidecar-last) "
                "or sanction the file in [tool.raftlint.rtl007]")


ALL_RULES = [RTL001(), RTL002(), RTL003(), RTL004(), RTL005(), RTL006(),
             RTL007()]
RULES_BY_CODE = {r.code: r for r in ALL_RULES}
