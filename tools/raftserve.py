#!/usr/bin/env python
"""raftserve — the always-on sweep service's command line.

Subcommands::

    raftserve serve --design Vertical_cylinder --port 8765 \
                    [--journal-dir DIR] [--successor URL]
        Long-lived HTTP endpoint over raft_tpu.serve.SweepService:
          POST /submit   {"hs":2.0,"tp":9.0,"heading_deg":0,
                          "deadline_s":60, "wait":false}
                         -> 202 {"request_id": ...} (or the full result
                         with "wait": true); admission rejection maps
                         to 429 + a Retry-After header.
          POST /drain    graceful restart handoff: stop admitting,
                         flush or journal in-flight work, write the
                         handoff manifest, shut down (SIGTERM does the
                         same).
          GET  /result?id=...      -> result by request id (404 unknown,
                                      202 still pending)
          GET  /result?digest=...  -> completed result by ledger digest
          GET  /stats | /healthz   -> service counters / liveness
        Ctrl-C drains the queue and writes the serve run manifest.
        With --journal-dir, every admission/result is write-ahead
        journaled before it is acknowledged, and a journal left by a
        predecessor (killed or drained) is recovered on boot: completed
        results re-delivered without re-solving, unfinished requests
        re-admitted, the program warm-started from the exec cache.

    raftserve soak [--requests 12] [--faults SPEC] [--json OUT]
        Deterministic chaos soak (raft_tpu/serve/soak.py): clean
        reference pass, then the same request schedule under fault
        injection + an admission burst; exits nonzero unless every
        completed request is digest-identical to the clean pass and
        the service survived with zero unhandled errors.  The fault
        spec defaults to serve.soak.DEFAULT_FAULTS, or comes from
        --faults / the RAFT_TPU_FAULTS environment variable.

    raftserve soak --kill-restart --journal-dir DIR [--kill-at N]
        Durability soak: a journaled child service is hard-killed
        mid-batch (kill@serve -> os._exit), then recovered against the
        same journal dir; exits nonzero unless the child died by the
        injected kill, zero accepted requests were lost, and every
        completed request is digest-identical to an uninterrupted
        clean run.

    raftserve soak --failover --journal-dir DIR [--kill-at N]
        Replication soak: the killed child's WAL mirrors to a peer
        store (DIR/mirror); the successor boots in a FRESH directory
        tree (DIR/successor — a different "host" that never reads
        DIR/primary) and recovers from only the mirror; exits nonzero
        unless zero accepted requests were lost across the host
        boundary and every digest is bit-for-bit identical to an
        uninterrupted clean run.

    raftserve soak --preempt --journal-dir DIR --ckpt-dir DIR \\
                   --store-dir DIR [--checkpoint-every N]
        Preemption soak (checkpoint/resume): a journaled,
        checkpoint-enabled child admits one design optimization and is
        hard-killed mid-descent at a segment boundary
        (kill@optimize:step=N); the successor recovers the WAL and
        resumes the descent from the newest valid checkpoint while an
        ENOSPC wave sheds checkpointing then the result-store
        write-through (typed StorageExhausted, self-clearing); exits
        nonzero unless the resumed design digest is bit-for-bit
        identical to an uninterrupted clean run, resumed_from_step >=
        checkpoint_every, zero requests were lost, and zero corrupt
        bytes were served.

    raftserve soak --storm --store-dir DIR [--journal-dir DIR]
        Result-tier soak: duplicate-heavy traffic over a persistent
        content-addressed store, a cross-replica read wave, a
        corrupt@resultstore integrity wave, and an audited neighbor
        warm-start wave; exits nonzero unless N duplicate requests
        over D distinct digests perform exactly D solves, zero
        corrupt bytes are ever served, and every digest (warm starts
        included) is bit-for-bit identical to the clean run.

    raftserve soak --elastic --journal-dir DIR
        Elastic-fleet soak (raft_tpu/serve/fleet.py): a
        FleetController boots real replica subprocesses under an
        open-loop load ramp — scale-up past the queue-depth threshold,
        a kill@fleet:replica=0 preemption wave whose WAL mirror is
        folded into a survivor via POST /recover (its accepted descent
        resumes from the newest valid checkpoint while
        enospc@checkpoint sheds the survivor's next checkpoint
        writes), load drop, then a drained scale-down that deregisters
        only after the handoff manifest lands; exits nonzero unless
        zero accepted requests were lost, every digest (the resumed
        descent's included) is bit-for-bit identical to an
        uninterrupted clean run, and a restarted controller rebuilds
        the same fleet view from its event journal.

    raftserve fleet --root DIR [--min-replicas N] [--max-replicas N]
        Elastic autoscaling control plane: boots/retires `raftserve
        serve` replica subprocesses against directory-shaped stores
        under --root, watches queue depth, admission p99 and quota
        pressure against scale thresholds (hysteresis + cooldown),
        folds preempted members' WAL mirrors into survivors, and
        fronts the fleet with the replica router on --port.  Every
        membership transition is journaled to --root/fleet.events.jsonl
        before it is acted on, so a killed controller recovers its
        fleet view on restart.

    raftserve distill --store-dir DIR --surrogate-dir DIR \\
                      [--tenant NAME] [--steps N] [--hidden 32,32]
        Train the learned read tier offline from the result-store
        corpus (raft_tpu/serve/surrogate.py): export every
        sidecar-verified full-mode entry for the tenant, fit the
        per-tenant MLP, calibrate a conformal error bound per output
        channel on a holdout split, and publish a versioned,
        digest-stamped bundle (pointer written last — a torn publish
        leaves the previous bundle live; a fresh publish clears any
        quarantine marker).  A running `raftserve serve
        --surrogate-dir` picks the new bundle up on its next lookup.

    raftserve route --backend URL [--backend URL ...] [--port N]
                    [--secret-file F] [--quota TENANT=RATE[:BURST]]
                    [--default-quota RATE[:BURST]]
        Replica router (raft_tpu/serve/router.py): one front door over
        N raftserve replicas — /healthz-swept backends, shared-secret
        auth (X-Raft-Auth), per-tenant token-bucket quotas (429 +
        Retry-After; one tenant's burst never starves another),
        tenant-affinity routing (warm programs stay warm) with
        failover, and fetches re-resolved by request digest against
        the survivors when the owning replica dies.

With --journal-dir (and --mirror-dir peers), every admission/result
is write-ahead journaled (and mirrored) before it is acknowledged;
--recover-from replays a dead peer's mirror at boot (the cross-host
failover: fresh journal tree, the dead host's disk never read).
With --store-dir the service adds the persistent content-addressed
result tier: exact-digest repeats return at memory speed (across
restarts and replicas sharing the directory), duplicate in-flight
submissions coalesce onto one solve, and --warm-start seeds misses
from the nearest cached neighbor under a divergence guard; `route
--store-dir` answers digest fetches from the same store before
proxying.
Set RAFT_TPU_OBS_DIR to collect the serve manifests, flight-recorder
event streams, and the trend-store rows the `obsctl slo` serve rules
gate on.  On a host with a TPU tunnel problem set JAX_PLATFORMS=cpu.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_fowts(args):
    """(fowt, coarse_fowt) on the requested frequency grid — one
    recipe (serve.soak.build_fowt) for the CLI, the soak harness, and
    its killed subprocess, so every phase solves identical physics."""
    from raft_tpu.serve.soak import build_fowt

    fowt = build_fowt(args.design, args.min_freq, args.max_freq,
                      args.dfreq)
    coarse = build_fowt(args.design, args.min_freq, args.max_freq,
                        args.dfreq * 2.0) if args.coarse else None
    return fowt, coarse


def cmd_soak(args) -> int:
    from raft_tpu.serve import soak
    from raft_tpu.serve.config import ServeConfig

    if args.elastic:
        if not args.journal_dir:
            print("raftserve soak --elastic needs --journal-dir "
                  "(the fleet root)", file=sys.stderr)
            return 2
        report = soak.run_elastic(
            args.design, root=args.journal_dir,
            min_freq=args.min_freq, max_freq=args.max_freq,
            dfreq=args.dfreq, checkpoint_every=args.checkpoint_every,
            seed=args.seed, timeout_s=args.timeout)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1, default=str)
        fl = report["fleet"]
        print(f"raftserve elastic soak: "
              f"{'OK' if report['ok'] else 'FAILED'} — replicas "
              f"{report['min_replicas']}->{fl['fleet_replicas_max']} "
              f"(ups={fl['fleet_scale_ups']} "
              f"downs={fl['fleet_scale_downs']} "
              f"preemptions={fl['fleet_preemptions']} "
              f"folds={fl['fleet_folds']}), "
              f"{report['completed']}/{report['n_requests']} "
              f"digest-exact, {fl['fleet_scale_loss_count']} lost; "
              f"descent resumed from step "
              f"{fl['fleet_resumed_from_step']} digest "
              f"{'MATCH' if not fl['fleet_preempt_digest_mismatch'] else 'MISMATCH'}, "
              f"ckpt sheds={fl['fleet_ckpt_shed']}; controller view "
              f"{'recovered' if report['controller_view_ok'] else 'DIVERGED'}, "
              f"{report['wall_s']:.1f}s")
        return 0 if report["ok"] else 1

    if args.preempt:
        if not (args.journal_dir and args.ckpt_dir and args.store_dir):
            print("raftserve soak --preempt needs --journal-dir, "
                  "--ckpt-dir and --store-dir", file=sys.stderr)
            return 2
        report = soak.run_preempt(
            args.design, journal_dir=args.journal_dir,
            ckpt_dir=args.ckpt_dir, store_dir=args.store_dir,
            min_freq=args.min_freq, max_freq=args.max_freq,
            dfreq=args.dfreq, checkpoint_every=args.checkpoint_every,
            kill_at_step=args.kill_at_step, seed=args.seed,
            timeout_s=args.timeout)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1, default=str)
        print(f"raftserve preemption soak: "
              f"{'OK' if report['ok'] else 'FAILED'} — child "
              f"rc={report['child_rc']}, resumed from step "
              f"{report['ckpt_resumed_from_step']} "
              f"(every={report['checkpoint_every']}), digest "
              f"{'MATCH' if not report['ckpt_resume_digest_mismatch'] else 'MISMATCH'}, "
              f"sheds ckpt={report['ckpt_shed']} "
              f"store={report['store_shed']}, "
              f"{report['storage_corrupt_served_count']} corrupt "
              f"served, {report['preempt_lost']} lost; "
              f"traces {report['trace']['trace_orphan_spans']} "
              f"orphan(s) {report['trace']['trace_resume_links']} "
              f"resume link(s), {report['wall_s']:.1f}s")
        return 0 if report["ok"] else 1

    if args.storm:
        if not args.store_dir:
            print("raftserve soak --storm needs --store-dir",
                  file=sys.stderr)
            return 2
        report = soak.run_storm(
            args.design, store_dir=args.store_dir,
            journal_dir=args.journal_dir, min_freq=args.min_freq,
            max_freq=args.max_freq, dfreq=args.dfreq,
            n_requests=args.requests, n_distinct=args.distinct,
            batch_cases=args.batch, seed=args.seed,
            timeout_s=args.timeout)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1, default=str)
        print(f"raftserve duplicate-storm soak: "
              f"{'OK' if report['ok'] else 'FAILED'} — "
              f"{report['n_requests']} requests / "
              f"{report['n_distinct']} distinct: {report['solves']} "
              f"solve(s) in {report['runner_calls_storm']} runner "
              f"call(s), {report['coalesced']} coalesced; "
              f"{report['store_corrupt_detected']} corruption(s) "
              f"detected, {report['store_corrupt_served_count']} "
              f"served; warm savings="
              f"{report['warm_start_iter_savings']} iters, "
              f"{report['warm_start_digest_mismatch']} mismatch(es); "
              f"{report['wall_s']:.1f}s")
        return 0 if report["ok"] else 1

    if args.failover:
        if not args.journal_dir:
            print("raftserve soak --failover needs --journal-dir",
                  file=sys.stderr)
            return 2
        report = soak.run_failover(
            args.design, journal_dir=args.journal_dir,
            min_freq=args.min_freq, max_freq=args.max_freq,
            dfreq=args.dfreq, n_requests=args.requests,
            kill_at=args.kill_at, batch_cases=args.batch,
            seed=args.seed, timeout_s=args.timeout)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1, default=str)
        rec = report["recover"]
        print(f"raftserve failover soak: "
              f"{'OK' if report['ok'] else 'FAILED'} — child "
              f"rc={report['child_rc']}, "
              f"{report['mirror_admitted']}/{report['n_requests']} "
              f"admits on the mirror, "
              f"{report['pre_kill_completed']} completed pre-kill, "
              f"{rec['recovered']} recovered / {rec['replayed']} "
              f"replayed / {rec['deduped']} deduped from the mirror "
              f"alone, {len(report['lost'])} lost, "
              f"{len(report['digest_mismatches'])} digest mismatch(es), "
              f"warm_start={report['restart_warm_start']}, "
              f"traces {report['trace']['trace_count']}"
              f"/{report['n_requests']} "
              f"{report['trace']['trace_orphan_spans']} orphan(s) "
              f"{report['trace']['trace_resume_links']} resume link(s), "
              f"{report['wall_s']:.1f}s")
        return 0 if report["ok"] else 1

    if args.kill_restart:
        if not args.journal_dir:
            print("raftserve soak --kill-restart needs --journal-dir",
                  file=sys.stderr)
            return 2
        report = soak.run_kill_restart(
            args.design, journal_dir=args.journal_dir,
            min_freq=args.min_freq, max_freq=args.max_freq,
            dfreq=args.dfreq, n_requests=args.requests,
            kill_at=args.kill_at, batch_cases=args.batch,
            seed=args.seed, timeout_s=args.timeout)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1, default=str)
        rec = report["recover"]
        print(f"raftserve kill-restart soak: "
              f"{'OK' if report['ok'] else 'FAILED'} — child "
              f"rc={report['child_rc']}, "
              f"{report['pre_kill_completed']} completed pre-kill, "
              f"{rec['recovered']} recovered / {rec['replayed']} "
              f"replayed / {rec['deduped']} deduped, "
              f"{len(report['lost'])} lost, "
              f"{len(report['digest_mismatches'])} digest mismatch(es), "
              f"warm_start={report['restart_warm_start']}, "
              f"{report['wall_s']:.1f}s")
        return 0 if report["ok"] else 1

    spec = (args.faults or os.environ.get("RAFT_TPU_FAULTS", "").strip()
            or soak.DEFAULT_FAULTS)
    fowt, coarse = _build_fowts(args)
    cfg = soak.default_config(batch_cases=args.batch)
    if args.queue_max:
        cfg = ServeConfig(**{**cfg.__dict__, "queue_max": args.queue_max})
    report = soak.run_soak(fowt, coarse_fowt=coarse, config=cfg,
                           n_requests=args.requests, faults_spec=spec,
                           seed=args.seed, timeout_s=args.timeout)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, default=str)
    chaos = report["chaos"]
    print(f"raftserve soak: {'OK' if report['ok'] else 'FAILED'} — "
          f"{report['completed']}/{report['n_requests']} digest-exact, "
          f"{len(report['failures'])} typed failure(s), "
          f"{report['burst_rejected']} burst reject(s), "
          f"{chaos['retries']} retries "
          f"({chaos['retried_recovered']} recovered), "
          f"{chaos['deadline_misses']} deadline miss(es), "
          f"mode={chaos['mode']}, {report['wall_s']:.1f}s")
    return 0 if report["ok"] else 1


def make_serve_server(service, host: str = "127.0.0.1", port: int = 0, *,
                      successor: str = None, deadline_s: float = 60.0,
                      tickets_max: int = 1024):
    """The replica's ThreadingHTTPServer over ``service`` (submit,
    optimize, result, drain, stats, healthz, metrics).  Module-level —
    not inlined in :func:`cmd_serve` — so the Prometheus
    exposition-conformance tests can stand up the REAL /metrics
    endpoint without booting a FOWT.  The returned server carries
    ``track_ticket`` (bounded-FIFO ticket registration, used by journal
    recovery) as an attribute."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from raft_tpu import errors
    from raft_tpu.obs.tracing import TRACE_HEADER

    # bounded FIFO, like SweepService._delivered: an always-on process
    # must not retain one ticket per request forever
    import collections
    tickets: collections.OrderedDict[str, object] = \
        collections.OrderedDict()

    def _track(t):
        tickets[t.id] = t
        while len(tickets) > tickets_max:
            tickets.popitem(last=False)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):                     # pragma: no cover
            pass

        def _send(self, code: int, doc: dict, headers: dict = None):
            data = json.dumps(doc, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):                              # noqa: N802
            from urllib.parse import parse_qs, urlparse
            url = urlparse(self.path)
            q = parse_qs(url.query)
            if url.path == "/healthz":
                self._send(200, {"ok": True, "pid": os.getpid(),
                                 **service.stats()})
            elif url.path == "/stats":
                self._send(200, service.summary())
            elif url.path == "/metrics":
                # Prometheus text exposition of THIS replica's registry
                # (scrape target; see docs/observability.md)
                from raft_tpu.obs import metrics as M
                data = M.exposition().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif url.path == "/result":
                digest = q.get("digest", [None])[0]
                rdigest = q.get("rdigest", [None])[0]
                rid = q.get("id", [None])[0]
                if digest or rdigest:
                    # rdigest= fetches by the REQUEST's content address
                    # — how a router re-resolves a dead replica's
                    # in-flight fetch against this (successor) process
                    res = (service.fetch(digest) if digest
                           else service.fetch_rdigest(rdigest))
                    if res is None:
                        self._send(404, {"error": "unknown digest"})
                    else:
                        self._send(200, res.to_dict())
                    return
                t = tickets.get(rid)
                if t is None:
                    self._send(404, {"error": "unknown request id"})
                elif not t.done():
                    self._send(202, {"request_id": rid,
                                     "status": "pending"})
                else:
                    self._send(200, t.result(0.0).to_dict())
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):                             # noqa: N802
            import math
            if self.path == "/drain":
                # graceful handoff: flush/journal everything, write the
                # handoff manifest, answer with it, then shut down
                doc = service.drain(successor=successor)
                self._send(200, doc)
                threading.Thread(target=srv.shutdown,
                                 daemon=True).start()
                return
            if self.path == "/recover":
                # runtime WAL fold: replay a dead peer's journal/mirror
                # directory into THIS running replica (recover() claims
                # fresh seqs for collisions and re-journals the foreign
                # admits).  The fleet controller's preemption path —
                # the survivor adopts the preempted member's accepted-
                # unfinished work, descents resuming from their newest
                # valid checkpoints.
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    src = str(doc["journal_dir"])
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"bad request: {e}"})
                    return
                try:
                    info = service.recover(src)
                except errors.ModelConfigError as e:
                    self._send(400, e.context())
                    return
                for t in info["tickets"].values():
                    _track(t)
                self._send(200, {k: info.get(k) for k in
                                 ("recovered", "replayed", "deduped",
                                  "corrupt", "ckpt_records", "mirror")})
                return
            if self.path in ("/optimize", "/farm"):
                # long-request tenants: /optimize takes bounds +
                # objective and answers with a journaled
                # digest-addressed optimized design; /farm takes a
                # turbine layout + per-case sea states/wind and answers
                # with the batched N x M farm solve (one compiled
                # program, layout-salted digest)
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(doc, dict):
                        raise ValueError("body must be a JSON object")
                    tenant = str(doc.pop("tenant", "default"))
                    wait = doc.pop("wait", False)
                    deadline_s_req = doc.pop("deadline_s", None)
                    if deadline_s_req is not None:
                        deadline_s_req = float(deadline_s_req)
                        if not (deadline_s_req > 0.0):
                            raise ValueError("deadline_s must be > 0")
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"bad request: {e}"})
                    return
                submit = (service.submit_farm if self.path == "/farm"
                          else service.submit_optimize)
                try:
                    t = submit(
                        doc, deadline_s=deadline_s_req, tenant=tenant,
                        trace=self.headers.get(TRACE_HEADER))
                except errors.AdmissionRejected as e:
                    self._send(429, e.context(),
                               headers={"Retry-After":
                                        f"{max(1, round(e.retry_after_s))}"})
                    return
                except errors.ModelConfigError as e:
                    self._send(400, e.context())
                    return
                _track(t)
                thdr = ({TRACE_HEADER: t.trace.to_header()}
                        if t.trace else {})
                if wait:
                    try:
                        res = t.result((deadline_s_req or deadline_s)
                                       + 5.0)
                    except errors.DeadlineExceeded as e:
                        self._send(504, e.context())
                        return
                    self._send(200, res.to_dict(), headers=thdr)
                else:
                    self._send(202, {"request_id": t.id, "seq": t.seq,
                                     "trace": (t.trace.as_dict()
                                               if t.trace else None)},
                               headers=thdr)
                return
            if self.path != "/submit":
                self._send(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(n) or b"{}")
                hs = float(doc["hs"])
                tp = float(doc["tp"])
                beta = (math.radians(float(doc["heading_deg"]))
                        if "heading_deg" in doc
                        else float(doc.get("heading_rad", 0.0)))
                tenant = str(doc.get("tenant", "default"))
                deadline_s_req = doc.get("deadline_s")
                if deadline_s_req is not None:
                    deadline_s_req = float(deadline_s_req)
                    if not (deadline_s_req > 0.0):
                        raise ValueError("deadline_s must be > 0")
            except (KeyError, TypeError, ValueError,
                    json.JSONDecodeError) as e:
                self._send(400, {"error": f"bad request: {e}"})
                return
            try:
                # the tenant RIDES the submission: the journaled
                # rdigest is tenant-salted, and the router's
                # re-resolution/dedupe contracts depend on backend and
                # router computing the SAME digest
                t = service.submit(hs, tp, beta,
                                   deadline_s=deadline_s_req,
                                   tenant=tenant,
                                   trace=self.headers.get(TRACE_HEADER))
            except errors.AdmissionRejected as e:
                self._send(429, e.context(),
                           headers={"Retry-After":
                                    f"{max(1, round(e.retry_after_s))}"})
                return
            except errors.ModelConfigError as e:
                # unknown tenant: this replica does not carry the model
                self._send(400, e.context())
                return
            _track(t)
            # echo the continued context: async callers correlate the
            # 202 with the eventual result (and with `obsctl trace`)
            thdr = ({TRACE_HEADER: t.trace.to_header()}
                    if t.trace else {})
            if doc.get("wait"):
                try:
                    res = t.result((deadline_s_req or deadline_s) + 5.0)
                except errors.DeadlineExceeded as e:
                    self._send(504, e.context())
                    return
                self._send(200, res.to_dict(), headers=thdr)
            else:
                self._send(202, {"request_id": t.id, "seq": t.seq,
                                 "trace": (t.trace.as_dict()
                                           if t.trace else None)},
                           headers=thdr)

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.track_ticket = _track
    return srv


def cmd_serve(args) -> int:
    import signal
    import threading

    from raft_tpu.serve import ServeConfig, SweepService
    from raft_tpu.serve import journal as wal

    fowt, coarse = _build_fowts(args)
    cfg = ServeConfig(nIter=args.niter, tol=args.tol,
                      fp_chunk=args.fp_chunk,
                      batch_cases=args.batch, queue_max=args.queue_max,
                      deadline_s=args.deadline,
                      batch_deadline_s=args.batch_deadline,
                      journal_dir=args.journal_dir,
                      mirror_dirs=tuple(args.mirror_dir or ()),
                      ckpt_dir=args.ckpt_dir,
                      checkpoint_every=args.checkpoint_every,
                      store_dir=args.store_dir,
                      warm_start=bool(args.warm_start),
                      surrogate_dir=args.surrogate_dir,
                      surrogate_tol=args.surrogate_tol,
                      surrogate_audit_every=args.surrogate_audit_every)
    degraded = {"coarse": coarse} if coarse is not None else None
    service = SweepService(fowt, cfg, degraded_fowts=degraded)
    srv = make_serve_server(service, args.host, args.port,
                            successor=args.successor,
                            deadline_s=cfg.deadline_s)
    # crash recovery: a journal left by a predecessor (killed or
    # drained) replays BEFORE the worker starts — completed results
    # become fetchable, unfinished requests re-enter the queue under
    # their original seqs, and their tickets are trackable by id.
    # --recover-from points at a FOREIGN directory (a dead peer's WAL
    # mirror): this process journals into its own --journal-dir and
    # replays the mirror — the cross-host failover boot
    # OWN journal first, then the foreign mirror: the own journal's
    # pending requests keep their original seqs (deterministic backoff
    # keys), and its completed results are in the dedupe index before
    # the mirror's duplicates replay
    sources = []
    if args.journal_dir and \
            os.path.exists(wal.journal_path(args.journal_dir)):
        sources.append(args.journal_dir)
    if args.recover_from:
        sources.append(args.recover_from)
    for src in sources:
        info = service.recover(src)
        for t in info["tickets"].values():
            srv.track_ticket(t)
        print(f"raftserve: journal recovery from {src}"
              f"{' (mirror/failover)' if info['mirror'] else ''} — "
              f"{info['recovered']} result(s) restored, "
              f"{info['replayed']} request(s) replayed, "
              f"{info['deduped']} deduped, "
              f"{info['corrupt']} corrupt line(s) skipped", flush=True)
    service.start()
    host, port = srv.server_address[:2]

    def _on_sigterm(signum, frame):                    # pragma: no cover
        # SIGTERM = orchestrated restart: drain (handoff manifest, WAL
        # pending records) on a side thread — a signal handler must not
        # block — then stop accepting connections
        def _drain():
            service.drain(successor=args.successor)
            srv.shutdown()
        threading.Thread(target=_drain, daemon=True).start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    print(f"raftserve: http://{host}:{port}/  (submit, optimize, farm, "
          f"result, drain, recover, "
          f"stats, healthz, metrics; design={args.design}, "
          f"batch={cfg.batch_cases}, "
          f"ladder={'->'.join(service.ladder)}, "
          f"journal={args.journal_dir or 'off'})", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:                          # pragma: no cover
        pass
    finally:
        srv.server_close()
        summary = service.stop()
        print(json.dumps(summary, indent=1, default=str))
    return 0


def cmd_fleet(args) -> int:
    import signal
    import threading

    from raft_tpu.serve.fleet import FleetConfig, FleetController
    from raft_tpu.serve.router import make_server

    cfg = FleetConfig(
        root=args.root, design=args.design, min_freq=args.min_freq,
        max_freq=args.max_freq, dfreq=args.dfreq,
        batch_cases=args.batch, queue_max=args.queue_max or 64,
        nIter=args.niter, tol=args.tol, fp_chunk=args.fp_chunk,
        ckpt_dir=args.ckpt_dir,
        checkpoint_every=args.checkpoint_every,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        scale_up_queue_depth=args.scale_up_queue_depth,
        scale_down_queue_depth=args.scale_down_queue_depth,
        hysteresis_ticks=args.hysteresis, cooldown_s=args.cooldown,
        tick_s=args.tick, host=args.host)
    ctl = FleetController(cfg).start()
    # the fleet's front door is the controller's router: callers see
    # one logical service while membership changes under them
    srv = make_server(ctl.router, args.host, args.port)
    host, port = srv.server_address[:2]
    print(f"raftserve fleet: http://{host}:{port}/  (router front "
          f"door; {len(ctl.live())} replica(s) live, "
          f"min={cfg.min_replicas} max={cfg.max_replicas}, "
          f"up@depth>={cfg.scale_up_queue_depth:g} "
          f"down@depth<={cfg.scale_down_queue_depth:g}, "
          f"hysteresis={cfg.hysteresis_ticks} tick(s), "
          f"cooldown={cfg.cooldown_s:g}s, root={ctl.root})", flush=True)

    def _shutdown(signum=None, frame=None):            # pragma: no cover
        threading.Thread(target=srv.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:                          # pragma: no cover
        pass
    finally:
        srv.server_close()
        counts = ctl.stop(drain=True)
        print(json.dumps(counts, indent=1, default=str))
    return 0


def cmd_distill(args) -> int:
    from raft_tpu import errors
    from raft_tpu.serve import surrogate
    from raft_tpu.serve.resultstore import ResultStore

    hidden = tuple(int(v) for v in str(args.hidden).split(",") if v)
    store = ResultStore(args.store_dir)
    try:
        res = surrogate.distill(
            store, args.surrogate_dir, tenant=args.tenant,
            hidden=hidden, steps=args.steps, lr=args.lr,
            seed=args.seed, holdout_frac=args.holdout_frac,
            alpha=args.alpha, min_rows=args.min_rows)
    except errors.ModelConfigError as e:
        print(f"raftserve distill: {e}", file=sys.stderr)
        return 1
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1, default=str)
    c = res["counts"]
    print(f"raftserve distill: tenant={res['tenant']} "
          f"v{res['version']} {os.path.basename(res['path'])} — "
          f"{res['corpus_rows']} corpus rows "
          f"({c['skipped_orphan']} orphan, "
          f"{c['skipped_quarantined']} quarantined, "
          f"{c['skipped_corrupt']} corrupt, "
          f"{c['skipped_degraded']} degraded skipped), "
          f"{res['holdout_rows']} holdout, "
          f"bound_rel_max={res['bound_rel_max']:.4f} "
          f"(serves under tol >= that), "
          f"loss {res['fit']['loss_first']:.3g} -> "
          f"{res['fit']['loss_last']:.3g}")
    return 0


def cmd_route(args) -> int:
    import threading

    from raft_tpu.serve.router import (ReplicaRouter, make_server,
                                       parse_quota)

    secret = None
    if args.secret_file:
        with open(args.secret_file, encoding="utf-8") as f:
            secret = f.read().strip()
        if not secret:
            print("raftserve route: --secret-file is empty",
                  file=sys.stderr)
            return 2
    quotas = {}
    for spec in (args.quota or []):
        tenant, _, q = spec.partition("=")
        if not tenant or not q:
            print(f"raftserve route: bad --quota {spec!r} "
                  "(want TENANT=RATE[:BURST])", file=sys.stderr)
            return 2
        quotas[tenant.strip()] = parse_quota(q)
    default_quota = (parse_quota(args.default_quota)
                     if args.default_quota else None)
    router = ReplicaRouter(
        args.backend, secret=secret, quotas=quotas,
        default_quota=default_quota,
        health_interval_s=args.health_interval,
        timeout_s=args.timeout, store_dir=args.store_dir).start()
    srv = make_server(router, args.host, args.port)
    host, port = srv.server_address[:2]
    healthy = sum(1 for b in router.backends if b.healthy)
    qdesc = ",".join(sorted(quotas)) \
        or ("default" if default_quota else "off")
    print(f"raftserve route: http://{host}:{port}/  (submit, result, "
          f"stats, healthz, metrics; {len(router.backends)} replica(s), "
          f"{healthy} healthy; quotas={qdesc}; "
          f"auth={'on' if secret else 'off'})", flush=True)

    def _shutdown(signum=None, frame=None):            # pragma: no cover
        threading.Thread(target=srv.shutdown, daemon=True).start()

    import signal
    signal.signal(signal.SIGTERM, _shutdown)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:                          # pragma: no cover
        pass
    finally:
        srv.server_close()
        router.stop()
        print(json.dumps(router.stats(), indent=1, default=str))
    return 0


def _add_model_args(p):
    p.add_argument("--design", default="Vertical_cylinder",
                   help="vendored design name (raft_tpu/designs)")
    p.add_argument("--min-freq", type=float, default=0.05)
    p.add_argument("--max-freq", type=float, default=0.5)
    p.add_argument("--dfreq", type=float, default=0.05)
    p.add_argument("--batch", type=int, default=4,
                   help="fixed case-batch size of the warm program")
    p.add_argument("--queue-max", type=int, default=None,
                   help="admission queue watermark")
    p.add_argument("--coarse", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="build the decimated-grid model for the "
                        "'coarse' degradation rung")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="raftserve", description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("soak", help="deterministic chaos soak "
                                    "(exit 1 on any verdict failure)")
    _add_model_args(p)
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--faults", default=None,
                   help="fault spec (default: RAFT_TPU_FAULTS or the "
                        "built-in chaos spec)")
    p.add_argument("--seed", type=int, default=2026)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--json", help="write the full report to this path")
    p.add_argument("--kill-restart", action="store_true",
                   help="durability soak: SIGKILL a journaled child "
                        "service mid-batch, recover on the same "
                        "--journal-dir, gate zero-loss digest parity")
    p.add_argument("--failover", action="store_true",
                   help="replication soak: SIGKILL a child whose WAL "
                        "mirrors to a peer store, recover a successor "
                        "in a FRESH directory tree from only the "
                        "mirror, gate cross-host zero-loss parity")
    p.add_argument("--storm", action="store_true",
                   help="duplicate-storm soak (result tier): dup-heavy "
                        "traffic over a persistent content-addressed "
                        "store under corrupt@resultstore — gate "
                        "exactly-D solves, zero corrupt bytes served, "
                        "warm-start digest parity")
    p.add_argument("--journal-dir", default=None,
                   help="journal root directory (required with "
                        "--kill-restart / --failover)")
    p.add_argument("--store-dir", default=None,
                   help="result-store directory (required with "
                        "--storm)")
    p.add_argument("--distinct", type=int, default=4,
                   help="distinct request digests in the storm "
                        "(--storm)")
    p.add_argument("--kill-at", type=int, default=6,
                   help="request seq the kill@serve fault fires at")
    p.add_argument("--preempt", action="store_true",
                   help="preemption soak (checkpoint/resume): a "
                        "journaled, checkpoint-enabled child dies "
                        "mid-descent (kill@optimize:step=N); the "
                        "successor resumes from the newest valid "
                        "checkpoint under an ENOSPC wave — gate "
                        "resumed-digest parity, typed storage sheds, "
                        "zero loss, zero corrupt bytes")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint-store directory (required with "
                        "--preempt)")
    p.add_argument("--checkpoint-every", type=int, default=2,
                   help="descent steps per checkpointed segment "
                        "(--preempt)")
    p.add_argument("--kill-at-step", type=int, default=None,
                   help="descent step the kill@optimize fault fires "
                        "at (--preempt; default: checkpoint-every)")
    p.add_argument("--elastic", action="store_true",
                   help="elastic-fleet soak: a FleetController under "
                        "an open-loop load ramp — scale-up, a "
                        "kill@fleet preemption wave whose WAL mirror "
                        "folds into a survivor (descent resumes from "
                        "checkpoint under enospc@checkpoint), load "
                        "drop, drained scale-down — gate zero accepted-"
                        "request loss + bit-for-bit digest parity "
                        "(--journal-dir is the fleet root)")
    p.set_defaults(fn=cmd_soak)

    p = sub.add_parser("serve", help="HTTP endpoint over SweepService")
    _add_model_args(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8765)
    p.add_argument("--deadline", type=float, default=120.0,
                   help="default per-request deadline (s)")
    p.add_argument("--batch-deadline", type=float, default=60.0,
                   help="watchdog deadline per in-flight batch (s)")
    p.add_argument("--niter", type=int, default=10,
                   help="fixed-point solver iterations — fleet "
                        "replicas must agree for digest parity")
    p.add_argument("--tol", type=float, default=0.01,
                   help="fixed-point convergence tolerance")
    p.add_argument("--fp-chunk", type=int, default=2,
                   help="frequency-chunk width of the solver scan")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint-store directory: descents write "
                        "resumable segments here (share it across a "
                        "fleet so a survivor resumes a preempted "
                        "replica's descent)")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="descent steps per checkpointed segment "
                        "(0 = off; needs --ckpt-dir)")
    p.add_argument("--journal-dir", default=None,
                   help="write-ahead request journal directory; a "
                        "journal left by a predecessor is recovered "
                        "on boot (replay + warm start)")
    p.add_argument("--mirror-dir", action="append", default=None,
                   help="peer directory the WAL mirrors to (repeat "
                        "for several peers); a successor on another "
                        "host recovers from a mirror alone")
    p.add_argument("--recover-from", default=None,
                   help="replay a FOREIGN journal/mirror directory at "
                        "boot (a dead peer's WAL mirror) while "
                        "journaling into --journal-dir — the "
                        "cross-host failover boot")
    p.add_argument("--successor", default=None,
                   help="where a drain points rejected callers "
                        "(Retry-After context)")
    p.add_argument("--store-dir", default=None,
                   help="persistent content-addressed result store: "
                        "exact-digest repeats return at memory speed "
                        "across restarts/replicas, duplicates "
                        "single-flight onto one solve")
    p.add_argument("--warm-start", action="store_true",
                   help="seed cache-miss solves from the nearest "
                        "cold-solved store neighbor (guarded + "
                        "audited; needs --store-dir)")
    p.add_argument("--surrogate-dir", default=None,
                   help="learned read tier: directory of distilled "
                        "per-tenant surrogate bundles (`raftserve "
                        "distill`); in-hull queries under the "
                        "calibrated bound answer from one forward "
                        "pass, audited + quarantined (needs "
                        "--store-dir)")
    p.add_argument("--surrogate-tol", type=float, default=0.05,
                   help="max relative calibrated bound a bundle may "
                        "serve under")
    p.add_argument("--surrogate-audit-every", type=int, default=8,
                   help="cold-solve + compare every Nth "
                        "surrogate-served answer")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("distill",
                       help="train + publish the learned read tier "
                            "from the result-store corpus")
    p.add_argument("--store-dir", required=True,
                   help="result-store directory (the training corpus)")
    p.add_argument("--surrogate-dir", required=True,
                   help="bundle output directory (served by "
                        "`raftserve serve --surrogate-dir`)")
    p.add_argument("--tenant", default="default")
    p.add_argument("--hidden", default="32,32",
                   help="comma-separated MLP hidden widths")
    p.add_argument("--steps", type=int, default=1500)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--holdout-frac", type=float, default=0.25,
                   help="corpus fraction held out for calibration")
    p.add_argument("--alpha", type=float, default=0.1,
                   help="conformal miscoverage level (bound covers "
                        ">= 1-alpha of holdout errors)")
    p.add_argument("--min-rows", type=int, default=16)
    p.add_argument("--json", help="write the distill report to this "
                                  "path")
    p.set_defaults(fn=cmd_distill)

    p = sub.add_parser("fleet",
                       help="elastic autoscaling control plane over "
                            "raftserve replica subprocesses "
                            "(raft_tpu/serve/fleet.py)")
    _add_model_args(p)
    p.add_argument("--root", required=True,
                   help="fleet root directory: per-replica journal + "
                        "mirror trees, the shared checkpoint store, "
                        "and the controller's event journal")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8700,
                   help="router front-door port")
    p.add_argument("--niter", type=int, default=10)
    p.add_argument("--tol", type=float, default=0.01)
    p.add_argument("--fp-chunk", type=int, default=2)
    p.add_argument("--ckpt-dir", default=None,
                   help="shared checkpoint store (descents resume "
                        "across replicas after a preemption)")
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--scale-up-queue-depth", type=float, default=4.0,
                   help="scale up when any backend's queue depth "
                        "reaches this")
    p.add_argument("--scale-down-queue-depth", type=float, default=0.0,
                   help="scale down when the max queue depth is at or "
                        "below this")
    p.add_argument("--hysteresis", type=int, default=2,
                   help="consecutive breaching ticks before a scale "
                        "decision acts")
    p.add_argument("--cooldown", type=float, default=5.0,
                   help="minimum seconds between scale actions")
    p.add_argument("--tick", type=float, default=0.5,
                   help="control-loop cadence (s)")
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser("route", help="replica router over N raftserve "
                                     "backends (health checks, "
                                     "per-tenant quotas, auth, "
                                     "failover)")
    p.add_argument("--backend", action="append", required=True,
                   help="backend raftserve URL (repeat per replica)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8700)
    p.add_argument("--secret-file", default=None,
                   help="file holding the shared admission secret "
                        "(callers send it as X-Raft-Auth); omit for "
                        "an open router")
    p.add_argument("--quota", action="append", default=None,
                   metavar="TENANT=RATE[:BURST]",
                   help="per-tenant token-bucket quota (requests/s "
                        "[+ burst]); repeatable")
    p.add_argument("--default-quota", default=None,
                   metavar="RATE[:BURST]",
                   help="quota for tenants without an explicit one "
                        "(omit for unlimited)")
    p.add_argument("--health-interval", type=float, default=1.0,
                   help="seconds between backend /healthz sweeps")
    p.add_argument("--timeout", type=float, default=30.0,
                   help="per-proxied-request timeout (s)")
    p.add_argument("--store-dir", default=None,
                   help="the replicas' shared/mirrored result store: "
                        "digest fetches consult it locally before any "
                        "proxying (dead replicas stay readable)")
    p.set_defaults(fn=cmd_route)

    args = ap.parse_args(argv)
    if args.cmd == "serve" and args.queue_max is None:
        args.queue_max = 64
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
